"""Process-wide metrics registry: labeled counters, gauges, histograms.

The reference platform's only observability was per-unit wall-clock
accumulation surfaced on a tornado page (SURVEY.md 5.1); by PR 2 the
rebuild had regrown that pattern three times over (the engine's
LatencyStats + compile ledger, generate's serve-cache counters, the
StatusWriter timing dict).  This module is the ONE substrate they all
feed: a thread-safe registry of named metrics with fixed-ladder
histogram buckets, exported two ways —

* :meth:`MetricsRegistry.prometheus_text` — Prometheus text exposition
  (``/metrics`` in ``services/serve.py``, ``metrics.prom`` beside
  ``status.json``), and
* :meth:`MetricsRegistry.snapshot` — a JSON-able dict (embedded in
  ``status.json`` and attached to every bench record).

Pure stdlib: importing this module must never pull in jax (the status
server and the znicz-check CLI run on hosts with no accelerator stack).
Metric creation is get-or-create — two subsystems asking for the same
name share the series; asking with a conflicting kind/labelset is an
error, never a silent second ledger.
"""

from __future__ import annotations

import json
import math
import re
import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# One shared seconds ladder (~100 us .. 60 s) for every latency-shaped
# histogram: fixed buckets keep series comparable across subsystems and
# exposition size bounded regardless of traffic.
DEFAULT_TIME_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# The fraction ladder (0..1) for occupancy/utilization-shaped
# histograms — per-tick phase occupancy, attribution fractions.  Dense
# near the edges where "idle" vs "saturated" verdicts live.
DEFAULT_FRACTION_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5,
    0.6, 0.7, 0.8, 0.9, 0.95, 0.99, 1.0,
)


def quantile_from_cumulative(
    cum: Sequence[Tuple[float, float]], q: float
) -> Optional[float]:
    """Bucket-interpolated quantile from ``[(upper_bound,
    cumulative_count), ...]`` (last pair is the +Inf bucket).  None when
    empty.  Shared by the live histogram children, the fleet aggregator's
    merged series, and the SLO monitor's windowed deltas — one
    interpolation rule everywhere."""
    if not cum:
        return None
    total = cum[-1][1]
    if total <= 0:
        return None
    target = q * total
    lo = 0.0
    prev = 0.0
    for upper, acc in cum:
        if acc >= target:
            if upper == math.inf:
                return lo  # best finite estimate: last finite edge
            span = acc - prev
            frac = (target - prev) / span if span else 1.0
            return lo + (upper - lo) * frac
        lo = upper if upper != math.inf else lo
        prev = acc
    return lo


def fraction_le(
    cum: Sequence[Tuple[float, float]], threshold: float
) -> float:
    """Interpolated fraction of observations <= ``threshold`` from the
    same cumulative-bucket shape.  1.0 when the series is empty (no
    evidence of a violation).  The SLO monitor's "good fraction"."""
    if not cum:
        return 1.0
    total = cum[-1][1]
    if total <= 0:
        return 1.0
    lo = 0.0
    prev = 0.0
    for upper, acc in cum:
        if upper >= threshold:
            if upper == math.inf:
                # samples past the last finite edge sit above any finite
                # threshold: count only what is provably below
                return prev / total
            span = upper - lo
            frac = (threshold - lo) / span if span else 1.0
            return (prev + frac * (acc - prev)) / total
        lo = upper
        prev = acc
    return 1.0


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if v != v:
        return "NaN"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _CounterChild:
    """One labeled counter series (monotone non-decreasing)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up; got inc({amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _GaugeChild:
    """One labeled gauge series (settable level)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _HistogramChild:
    """One labeled histogram series over a fixed bucket ladder."""

    __slots__ = ("_lock", "_uppers", "_counts", "_sum", "_count")

    def __init__(self, lock: threading.RLock, uppers: Tuple[float, ...]):
        self._lock = lock
        self._uppers = uppers  # strictly increasing, last is +inf
        self._counts = [0] * len(uppers)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            # le semantics: the first upper bound >= v owns the sample
            self._counts[bisect_left(self._uppers, v)] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative_count)] including the +Inf bucket."""
        with self._lock:
            out, acc = [], 0
            for upper, n in zip(self._uppers, self._counts):
                acc += n
                out.append((upper, acc))
            return out

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate (None when empty)."""
        return quantile_from_cumulative(self.cumulative(), q)


class Metric:
    """A named metric family: one child series per label-value tuple."""

    def __init__(
        self,
        registry: "MetricsRegistry",
        name: str,
        help: str,
        kind: str,
        labelnames: Tuple[str, ...],
        buckets: Optional[Tuple[float, ...]] = None,
    ):
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = labelnames
        self.buckets = buckets
        self._registry = registry
        self._lock = registry._lock
        self._children: Dict[Tuple[str, ...], object] = {}
        if not labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        if self.kind == "counter":
            return _CounterChild(self._lock)
        if self.kind == "gauge":
            return _GaugeChild(self._lock)
        return _HistogramChild(self._lock, self.buckets)

    def labels(self, *values, **kv):
        """The child series for one label-value set (created on demand,
        capped at the registry's cardinality limit)."""
        if kv:
            if values:
                raise ValueError("pass labels positionally OR by name")
            try:
                values = tuple(str(kv[n]) for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e}; wants "
                    f"{self.labelnames}"
                ) from e
            if len(kv) != len(self.labelnames):
                extra = set(kv) - set(self.labelnames)
                raise ValueError(f"{self.name}: unknown label(s) {extra}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} wants labels {self.labelnames}; got "
                f"{values!r}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                if len(self._children) >= self._registry.max_series:
                    raise ValueError(
                        f"{self.name}: label cardinality exceeds "
                        f"{self._registry.max_series} series — a label "
                        "value is probably unbounded (request id, path)"
                    )
                child = self._children[values] = self._make_child()
            return child

    def children(self) -> Dict[Tuple[str, ...], object]:
        with self._lock:
            return dict(self._children)

    def reset(self) -> None:
        """Drop every child series (tests / explicit counter resets)."""
        with self._lock:
            self._children.clear()
            if not self.labelnames:
                self._children[()] = self._make_child()

    # unlabeled convenience: the metric IS its single series
    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; use .labels()"
            )
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    @property
    def value(self) -> float:
        return self._default().value


class MetricsRegistry:
    """Thread-safe get-or-create registry of named metric families."""

    def __init__(self, *, max_series_per_metric: int = 1000):
        self._lock = threading.RLock()
        self._metrics: Dict[str, Metric] = {}
        self.max_series = max_series_per_metric

    def _get_or_create(
        self, name, help, kind, labelnames, buckets=None
    ) -> Metric:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        labelnames = tuple(labelnames)
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln == "le":
                raise ValueError(f"invalid label name {ln!r} on {name}")
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (
                    existing.kind != kind
                    or existing.labelnames != labelnames
                    or (buckets is not None and existing.buckets != buckets)
                ):
                    raise ValueError(
                        f"metric {name} already registered as "
                        f"{existing.kind}{existing.labelnames}; cannot "
                        f"re-register as {kind}{labelnames}"
                    )
                return existing
            m = Metric(self, name, help, kind, labelnames, buckets)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labelnames=()) -> Metric:
        return self._get_or_create(name, help, "counter", labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Metric:
        return self._get_or_create(name, help, "gauge", labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames=(),
        buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
    ) -> Metric:
        finite = sorted({float(b) for b in buckets if b != math.inf})
        if not finite:
            raise ValueError(f"{name}: want at least one finite bucket")
        uppers = tuple(finite) + (math.inf,)
        return self._get_or_create(
            name, help, "histogram", labelnames, uppers
        )

    def metrics(self) -> Dict[str, Metric]:
        with self._lock:
            return dict(self._metrics)

    def reset(self) -> None:
        """Zero every series (test isolation; keeps registrations)."""
        for m in self.metrics().values():
            m.reset()

    # -- exports -----------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able dict of every series; histograms carry count/sum,
        bucket counts and interpolated p50/p95/p99 estimates."""
        out: Dict[str, dict] = {}
        for name, m in sorted(self.metrics().items()):
            series = []
            for values, child in sorted(m.children().items()):
                labels = dict(zip(m.labelnames, values))
                if m.kind == "histogram":
                    series.append(
                        {
                            "labels": labels,
                            "count": child.count,
                            "sum": child.sum,
                            "buckets": {
                                _fmt_value(u): c
                                for u, c in child.cumulative()
                            },
                            "p50": child.quantile(0.5),
                            "p95": child.quantile(0.95),
                            "p99": child.quantile(0.99),
                        }
                    )
                else:
                    series.append({"labels": labels, "value": child.value})
            out[name] = {"type": m.kind, "help": m.help, "series": series}
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition (format 0.0.4)."""
        lines: List[str] = []
        for name, m in sorted(self.metrics().items()):
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            for values, child in sorted(m.children().items()):
                base = list(zip(m.labelnames, values))
                if m.kind == "histogram":
                    for upper, acc in child.cumulative():
                        lines.append(
                            _sample(
                                f"{name}_bucket",
                                base + [("le", _fmt_value(upper))],
                                acc,
                            )
                        )
                    lines.append(_sample(f"{name}_sum", base, child.sum))
                    lines.append(_sample(f"{name}_count", base, child.count))
                else:
                    lines.append(_sample(name, base, child.value))
        return "\n".join(lines) + "\n"


def _sample(name: str, labels, value) -> str:
    if labels:
        inner = ",".join(
            f'{k}="{_escape_label(str(v))}"' for k, v in labels
        )
        return f"{name}{{{inner}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


# -- exposition parsing ----------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?\s*)*)\})?"
    r"\s+(\S+)(?:\s+\d+)?$"
)
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str) -> dict:
    """Strict-enough parser for the 0.0.4 text exposition.

    Returns ``{"types": {...}, "helps": {...}, "samples":
    [(name, labels_dict, value), ...]}`` and raises ``ValueError`` on
    any malformed line — the tier-1 acceptance check that ``/metrics``
    stays machine-readable, with no external scrape stack needed.
    """
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 3:
                raise ValueError(f"line {lineno}: malformed HELP: {line!r}")
            helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or parts[3] not in (
                "counter", "gauge", "histogram", "summary", "untyped"
            ):
                raise ValueError(f"line {lineno}: malformed TYPE: {line!r}")
            types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue  # plain comment
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        name, labelsrc, valuesrc = m.group(1), m.group(2), m.group(3)
        try:
            value = float(valuesrc)  # accepts +Inf/-Inf/NaN
        except ValueError as e:
            raise ValueError(
                f"line {lineno}: bad sample value {valuesrc!r}"
            ) from e
        labels = {}
        if labelsrc:
            for lm in _LABEL_PAIR_RE.finditer(labelsrc):
                labels[lm.group(1)] = (
                    lm.group(2)
                    .replace("\\n", "\n")
                    .replace('\\"', '"')
                    .replace("\\\\", "\\")
                )
        samples.append((name, labels, value))
    # histogram invariants: cumulative buckets and le=+Inf == _count
    for name, kind in types.items():
        if kind != "histogram":
            continue
        by_series: Dict[Tuple, List[Tuple[float, float]]] = {}
        counts: Dict[Tuple, float] = {}
        for sname, labels, value in samples:
            key = tuple(
                sorted((k, v) for k, v in labels.items() if k != "le")
            )
            if sname == f"{name}_bucket":
                le = labels.get("le")
                if le is None:
                    raise ValueError(
                        f"{name}_bucket sample missing 'le' label"
                    )
                by_series.setdefault(key, []).append((float(le), value))
            elif sname == f"{name}_count":
                counts[key] = value
        for key, edges in by_series.items():
            edges.sort()
            cum = [c for _, c in edges]
            if cum != sorted(cum):
                raise ValueError(f"{name}: non-cumulative buckets at {key}")
            if edges[-1][0] != math.inf:
                raise ValueError(f"{name}: missing le=+Inf bucket at {key}")
            if key in counts and counts[key] != edges[-1][1]:
                raise ValueError(
                    f"{name}: le=+Inf != _count at {key}"
                )
    return {"types": types, "helps": helps, "samples": samples}


# -- default (process-wide) registry ---------------------------------------

_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every subsystem feeds."""
    return _DEFAULT


def snapshot_json(indent: Optional[int] = None) -> str:
    return json.dumps(_DEFAULT.snapshot(), indent=indent)
