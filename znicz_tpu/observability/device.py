"""Device & compile telemetry: the program ledger behind /debug/programs.

"Zero new compiled programs" is this repo's core serving invariant, and
the remaining scheduling/kernel ROADMAP rungs all want per-program
timing and memory signals as input — yet until now nothing observed the
device side at all.  This module is that layer:

* **Program ledger** — every TRUE first compile of a serving program
  (the engine's ``admit``/``chunk``/``prefill``/``paged_chunk``/``cow``
  programs, deduped exactly like ``znicz_serve_compiles_total``; the
  ``generate_serve`` AOT cache) records one entry: compile wall time,
  the lowering's cost analysis (FLOPs / bytes accessed) and — where the
  jax version exposes it — the executable's memory analysis.  Served at
  ``GET /debug/programs``; the engine-sourced entry count matches the
  engine ledger and ``znicz_serve_compiles_total`` by construction.
* **Metrics** — ``znicz_compile_seconds{kind}`` (histogram),
  ``znicz_program_cost_flops_total{kind}`` /
  ``znicz_program_cost_bytes_total{kind}`` (static per-program costs,
  summed over compiles), ``znicz_device_memory_bytes{kind,device}``
  (executable sizes + live ``memory_stats`` where the backend reports
  them — CPU answers None and the gauges simply stay absent).
* **On-demand device capture** — :func:`capture_profile` runs a
  ``jax.profiler`` trace for N seconds (``POST /debug/profile`` on the
  serving surface), wrapped in a host span so the device capture lines
  up with the host timeline.

Every jax touch is lazy and failure-tolerant: on a host without an
accelerator stack (or a jax without the API) the helpers answer None /
empty and the serving path never notices — the graceful-no-op contract
the ISSUE pins for jax 0.4.37.
"""

from __future__ import annotations

import logging
import tempfile
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from znicz_tpu.observability.registry import get_registry
from znicz_tpu.observability.tracing import span

logger = logging.getLogger(__name__)

_LOCK = threading.Lock()
# ledger key -> entry dict, insertion (= compile) order
_PROGRAMS: "OrderedDict[str, dict]" = OrderedDict()

# jax.profiler device captures are process-global: one at a time
_PROFILE_LOCK = threading.Lock()
PROFILE_MAX_SECONDS = 30.0

_MEMORY_FIELDS = (
    "generated_code_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
)


def _m_compile_seconds():
    return get_registry().histogram(
        "znicz_compile_seconds",
        "wall time of true first compiles by program kind",
        ("kind",),
    )


def _m_cost_flops():
    return get_registry().counter(
        "znicz_program_cost_flops_total",
        "cost-analysis FLOPs of compiled programs, summed per kind",
        ("kind",),
    )


def _m_cost_bytes():
    return get_registry().counter(
        "znicz_program_cost_bytes_total",
        "cost-analysis bytes accessed of compiled programs, per kind",
        ("kind",),
    )


def _m_device_memory():
    return get_registry().gauge(
        "znicz_device_memory_bytes",
        "device memory by kind: executable sizes (summed over compiled "
        "programs) and live memory_stats where the backend reports them",
        ("kind", "device"),
    )


# -- cost / memory extraction (never raise) ---------------------------------


def stage_cost(stage) -> Optional[dict]:
    """Normalized ``cost_analysis()`` of a jax ``Lowered``/``Compiled``
    stage: ``{"flops": float|None, "bytes_accessed": float|None}``.
    None when the stage (or this jax) has no cost analysis."""
    try:
        c = stage.cost_analysis()
    except Exception:
        logger.debug("cost_analysis unavailable", exc_info=True)
        return None
    if isinstance(c, (list, tuple)):
        c = c[0] if c else None
    if not isinstance(c, dict):
        return None
    out = {}
    flops = c.get("flops")
    by = c.get("bytes accessed")
    out["flops"] = float(flops) if flops is not None else None
    out["bytes_accessed"] = float(by) if by is not None else None
    return out


def lowered_cost(fn, args, kwargs) -> Optional[dict]:
    """Cost analysis via a throwaway ``fn.lower(...)`` — tracing only,
    no second compile (jit's executable cache is keyed separately from
    AOT lowering, and lowering never touches buffer contents, so this
    is safe even before a donating call).  None on any failure."""
    try:
        lowered = fn.lower(*args, **(kwargs or {}))
    except Exception:
        logger.debug("lowering for cost analysis failed", exc_info=True)
        return None
    return stage_cost(lowered)


def compiled_memory(compiled) -> Optional[dict]:
    """Normalized ``memory_analysis()`` of a jax ``Compiled``: the
    ``*_size_in_bytes`` fields as a dict.  None when unavailable."""
    try:
        m = compiled.memory_analysis()
    except Exception:
        logger.debug("memory_analysis unavailable", exc_info=True)
        return None
    if m is None:
        return None
    out = {}
    for field in _MEMORY_FIELDS:
        v = getattr(m, field, None)
        if v is not None:
            out[field] = int(v)
    return out or None


# -- the ledger -------------------------------------------------------------


def record_program(
    key,
    compile_s: float,
    *,
    kind: Optional[str] = None,
    source: str = "engine",
    cost: Optional[dict] = None,
    memory: Optional[dict] = None,
    dedup=None,
) -> dict:
    """Ledger one compiled program.  ``key`` is the display key (the
    engine's program-ledger tuple, or the serve cache's); ``dedup``
    (default: the key itself) is the uniqueness key — the engine passes
    its ``(params-geometry, key)`` pair so two geometries compiling the
    same program key stay two entries, exactly like
    ``znicz_serve_compiles_total``.  Call ONLY on a true first compile;
    the caller owns that dedup (``DecodeEngine._program``)."""
    kind = kind if kind is not None else (
        key[0] if isinstance(key, tuple) and key else str(key)
    )
    entry = {
        "key": str(key),
        "kind": str(kind),
        "source": source,
        "compile_s": round(float(compile_s), 6),
        "flops": (cost or {}).get("flops"),
        "bytes_accessed": (cost or {}).get("bytes_accessed"),
        "memory": memory,
        "recorded_unix": time.time(),  # timestamp, not a delta
    }
    ledger_key = f"{source}:{dedup if dedup is not None else key}"
    with _LOCK:
        _PROGRAMS[ledger_key] = entry
    _m_compile_seconds().labels(kind=entry["kind"]).observe(
        float(compile_s)
    )
    if entry["flops"]:
        _m_cost_flops().labels(kind=entry["kind"]).inc(entry["flops"])
    if entry["bytes_accessed"]:
        _m_cost_bytes().labels(kind=entry["kind"]).inc(
            entry["bytes_accessed"]
        )
    if memory and memory.get("generated_code_size_in_bytes"):
        # executable footprint, accumulated across compiles
        with _LOCK:
            total = sum(
                (e.get("memory") or {}).get(
                    "generated_code_size_in_bytes", 0
                )
                for e in _PROGRAMS.values()
            )
        _m_device_memory().labels(
            kind="executable", device="all"
        ).set(float(total))
    return entry


def programs(source: Optional[str] = None) -> List[dict]:
    """The ledger entries in compile order (copies; filter by
    ``source`` — ``"engine"`` / ``"serve_cache"``)."""
    with _LOCK:
        return [
            dict(e) for e in _PROGRAMS.values()
            if source is None or e["source"] == source
        ]


def program_count(source: Optional[str] = None) -> int:
    with _LOCK:
        return sum(
            1 for e in _PROGRAMS.values()
            if source is None or e["source"] == source
        )


def compile_seconds_total() -> float:
    with _LOCK:
        return round(
            sum(e["compile_s"] for e in _PROGRAMS.values()), 6
        )


def ledger_snapshot() -> dict:
    """The ``/debug/programs`` body (also attached to bench records):
    the full entry list plus the headline counts the acceptance test
    pins against the engine ledger and ``znicz_serve_compiles_total``."""
    progs = programs()
    by_kind: Dict[str, int] = {}
    for e in progs:
        by_kind[e["kind"]] = by_kind.get(e["kind"], 0) + 1
    return {
        "programs": progs,
        "count": len(progs),
        "engine_count": sum(1 for e in progs if e["source"] == "engine"),
        "by_kind": by_kind,
        "compile_seconds_total": round(
            sum(e["compile_s"] for e in progs), 6
        ),
        "device_memory": device_memory(),
    }


# -- live device memory -----------------------------------------------------


def device_memory() -> List[dict]:
    """Per-device ``memory_stats()`` where the backend reports them
    (TPU/GPU; jax 0.4.37's CPU answers None — then the list carries
    the device with ``stats: null``).  Also refreshes the
    ``znicz_device_memory_bytes`` gauges.  Never raises; empty when
    jax itself is unavailable."""
    try:
        import jax

        devices = jax.devices()
    except Exception:
        logger.debug("jax devices unavailable", exc_info=True)
        return []
    out = []
    gauge = _m_device_memory()
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        name = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
        out.append({"device": name, "stats": stats})
        if stats:
            for stat_key, gauge_kind in (
                ("bytes_in_use", "in_use"),
                ("peak_bytes_in_use", "peak"),
                ("bytes_limit", "limit"),
            ):
                v = stats.get(stat_key)
                if v is not None:
                    gauge.labels(kind=gauge_kind, device=name).set(
                        float(v)
                    )
    return out


# -- on-demand device capture -----------------------------------------------


def capture_profile(
    seconds: float, log_dir: Optional[str] = None
) -> dict:
    """One bounded ``jax.profiler`` device capture (``POST
    /debug/profile?seconds=N``): start a trace, sleep ``seconds``
    (clamped to ``PROFILE_MAX_SECONDS``), stop, return the capture
    directory.  The capture runs inside a ``debug/profile`` host span,
    so the device tracks line up with the host timeline (the tracer
    already wraps every span in ``jax.profiler.TraceAnnotation``).

    Raises ``ValueError`` on a non-finite duration (the HTTP layer
    answers 400), ``RuntimeError`` when a capture is already running
    (409) or the profiler is unavailable (503)."""
    s = float(seconds)
    if s != s or s in (float("inf"), float("-inf")):
        # NaN slides through min/max clamps (every comparison False)
        # and time.sleep(nan) raises — reject it at the door
        raise ValueError(f"want a finite duration; got {seconds!r}")
    s = min(max(s, 0.01), PROFILE_MAX_SECONDS)
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise RuntimeError("a device profile capture is already running")
    try:
        try:
            import jax
        except Exception as exc:
            raise RuntimeError(f"jax unavailable: {exc}") from exc
        out_dir = log_dir or tempfile.mkdtemp(prefix="znicz-profile-")
        with span("debug/profile", seconds=s, log_dir=out_dir):
            try:
                jax.profiler.start_trace(out_dir)
            except Exception as exc:
                raise RuntimeError(
                    f"jax profiler unavailable: {exc}"
                ) from exc
            try:
                time.sleep(s)
            finally:
                jax.profiler.stop_trace()
        return {"log_dir": out_dir, "seconds": s}
    finally:
        _PROFILE_LOCK.release()
