"""Input-pipeline bottleneck attribution: where does a train step's wall go?

The streaming-rebuild rung (ROADMAP "the 100x training gap") cannot be
built blind: BENCH_r02-r04 measured 107-173 img/s streaming against
12,548 device-resident, and the only witness was one histogram
(``znicz_prefetch_wait_seconds``) that says "the consumer waited" but
not *why* — disk read, host decode, host->device transfer, or dispatch.
This module is the attribution layer on top of the per-stage
instrumentation:

* **Stage taxonomy** — the producer path (:mod:`znicz_tpu.loader
  .prefetch`) observes ``znicz_pipeline_stage_seconds{stage}`` for
  ``fetch`` (materializing one batch from the loader), ``host_transform``
  (decode/augment callables run in the producer thread) and ``enqueue``
  (blocked handing the batch over — depth exhaustion); the workflow's
  device-placement closure observes ``h2d`` through :class:`H2DProbe`
  (bytes moved + wall -> the live ``znicz_h2d_bytes_per_second`` gauge).
* **:class:`PipelineAttribution`** — decomposes the per-step wall clock
  (``znicz_train_step_wall_seconds``) into fractions (compute /
  prefetch-wait / h2d / other) that sum to ~1.0, names the bottleneck
  with a confidence band, and suggests the next move.  Reads a live
  registry, a JSON snapshot, or a Prometheus exposition — the same
  three sources ``tools/znicz-doctor`` accepts.

Attribution math: the consumer's step wall is sliced into *compute*
(the ``dispatch/*`` phases of ``znicz_train_phase_seconds``),
*prefetch-wait* (``znicz_prefetch_wait_seconds``) and *other* (the
residual — untimed host work: python loop, stacking).  H2D is then
carved out of whichever slice it actually ran in: with the prefetch
thread on, the producer's ``h2d`` share of its busy time prorates the
wait slice (while the consumer waits, the producer is in one of its
stages); with prefetching off the probe ran inline on the consumer, so
its seconds come out of the residual.  Either way the four fractions
are disjoint and sum to 1 (measurement jitter is renormalized away).

Pure stdlib — importing this module must never pull in jax (the doctor
CLI runs on hosts with no accelerator stack).
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

from znicz_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
    parse_prometheus_text,
)
from znicz_tpu.utils import faults

# the producer/consumer stage taxonomy (docs/OBSERVABILITY.md
# "Training observability")
STAGE_FETCH = "fetch"
STAGE_TRANSFORM = "host_transform"
STAGE_H2D = "h2d"
STAGE_ENQUEUE = "enqueue"

STEP_WALL_METRIC = "znicz_train_step_wall_seconds"
WAIT_METRIC = "znicz_prefetch_wait_seconds"
PHASE_METRIC = "znicz_train_phase_seconds"
STAGE_METRIC = "znicz_pipeline_stage_seconds"
H2D_BPS_METRIC = "znicz_h2d_bytes_per_second"
H2D_BYTES_METRIC = "znicz_h2d_bytes_total"
QUEUE_FULL_METRIC = "znicz_prefetch_queue_full_total"

# anomaly surfaces the doctor reads from the same exposition
ANOMALY_ACTIVE_METRIC = "znicz_train_anomaly_active"
ANOMALY_TOTAL_METRIC = "znicz_train_anomalies_total"
LAST_LOSS_METRIC = "znicz_train_last_loss"
LAST_GRAD_METRIC = "znicz_train_last_grad_norm"

# self-healing surfaces (docs/TRAINING.md): the training tier's
# detect->recover loop.  Defined HERE (stdlib-pure) so both the
# producers (workflow/recovery.py, launcher.py, loader/base.py) and the
# doctor's readout speak one name per signal.
ROLLBACKS_METRIC = "znicz_train_rollbacks_total"
ROLLBACK_GIVE_UP_METRIC = "znicz_train_rollback_give_up"
RESTARTS_METRIC = "znicz_train_restarts_total"
RESTART_BUDGET_METRIC = "znicz_train_restart_budget"
LOADER_RETRIES_METRIC = "znicz_loader_retries_total"
LOADER_SKIPPED_METRIC = "znicz_loader_skipped_batches_total"
SNAPSHOT_FAILURES_METRIC = "znicz_train_snapshot_failures_total"

# the families a warm-up window reset clears (bench/tests exclude the
# first epoch's compile stall from the attribution they report)
WINDOW_METRICS = (
    STEP_WALL_METRIC,
    WAIT_METRIC,
    PHASE_METRIC,
    STAGE_METRIC,
    H2D_BYTES_METRIC,
    QUEUE_FULL_METRIC,
)


def stage_seconds(registry: Optional[MetricsRegistry] = None):
    """The shared per-stage histogram family (get-or-create)."""
    reg = registry if registry is not None else get_registry()
    return reg.histogram(
        STAGE_METRIC,
        "input-pipeline per-stage wall seconds "
        "(fetch / host_transform / h2d / enqueue)",
        ("stage",),
    )


def step_wall_seconds(registry: Optional[MetricsRegistry] = None):
    """Consumer-side per-train-step wall histogram (get-or-create)."""
    reg = registry if registry is not None else get_registry()
    return reg.histogram(
        STEP_WALL_METRIC,
        "wall seconds per training step as seen by the consumer loop "
        "(prefetch wait + dispatch + host bookkeeping)",
    )


def reset_window(registry: Optional[MetricsRegistry] = None) -> None:
    """Zero the attribution-relevant series (warm-up exclusion: call
    after the compile epoch so the reported window is steady-state).
    Families that don't exist yet are simply skipped."""
    reg = registry if registry is not None else get_registry()
    fams = reg.metrics()
    for name in WINDOW_METRICS:
        m = fams.get(name)
        if m is not None:
            m.reset()


class H2DProbe:
    """Host->device transfer probe: bytes moved + wall time.

    ``with probe.measure(nbytes):`` around the device placement calls
    observes the ``h2d`` stage histogram, counts
    ``znicz_h2d_bytes_total`` and keeps the live
    ``znicz_h2d_bytes_per_second`` gauge fresh from a rolling window of
    recent transfers.  The wall measured is the *initiation* wall — on
    an async transport this under-reports link occupancy and
    over-reports bandwidth, so the gauge is a best-effort live signal,
    while the byte counter and stage histogram stay exact.

    The ``loader.h2d`` fault point fires inside the measured region, so
    an injected delay reads as a slow link to the attribution — the
    CI fixture for the h2d-bound verdict.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        *,
        window: int = 64,
    ):
        reg = registry if registry is not None else get_registry()
        self._hist = stage_seconds(reg)
        self._bytes = reg.counter(
            H2D_BYTES_METRIC,
            "bytes transferred host->device by the training loader path",
        )
        self._bps = reg.gauge(
            H2D_BPS_METRIC,
            "live host->device transfer rate over the last ~window of "
            "training batches",
        )
        self._recent: deque = deque(maxlen=max(int(window), 1))
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def measure(self, nbytes: int) -> Iterator[None]:
        faults.fire("loader.h2d")
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.observe(nbytes, dt)

    def observe(self, nbytes: int, seconds: float) -> None:
        self._hist.labels(stage=STAGE_H2D).observe(seconds)
        if nbytes > 0:
            self._bytes.inc(float(nbytes))
        with self._lock:
            self._recent.append((float(nbytes), float(seconds)))
            total_b = sum(b for b, _ in self._recent)
            total_s = sum(s for _, s in self._recent)
        if total_s > 0:
            self._bps.set(total_b / total_s)


# -- attribution ------------------------------------------------------------

_SUGGESTIONS = {
    "input": (
        "raise prefetch depth, shard loaders across processes, or move "
        "decode/augment on-device (the streaming-rebuild rung)"
    ),
    "h2d": (
        "overlap H2D with compute (double-buffered device prefetch), "
        "batch transfers, or ship compact dtypes (u8 + on-device "
        "normalize)"
    ),
    "compute": (
        "input pipeline keeps up — optimize the step itself or scale "
        "devices"
    ),
    "other": (
        "untimed host work dominates (python loop, stacking, metric "
        "sync) — record a tracer window to see where"
    ),
}

_VERDICTS = {
    "input": "input-bound",
    "h2d": "h2d-bound",
    "compute": "compute-bound",
    "other": "unattributed",
}


class PipelineAttribution:
    """Step-wall decomposition over one metrics capture.

    Construct from a live registry (:meth:`from_registry`), a registry
    JSON snapshot (:meth:`from_snapshot` — the ``status.json`` /
    bench-record shape, self-describing non-metric entries like
    ``{"type": "slo"}`` are skipped), or a Prometheus text exposition
    (:meth:`from_prometheus` — a ``metrics.prom`` file or an
    aggregator's merged ``/metrics``; pass ``instance=`` to scope a
    fleet exposition to one process).  :meth:`attribution` returns the
    self-describing ``{"type": "pipeline", ...}`` record the bench
    attaches and ``znicz-doctor`` prints.
    """

    def __init__(self, samples: List[Tuple[str, Dict[str, str], float]]):
        # prometheus-shaped flat samples: histograms appear as
        # <name>_sum / <name>_count / <name>_bucket rows
        self._samples = samples

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_registry(
        cls, registry: Optional[MetricsRegistry] = None
    ) -> "PipelineAttribution":
        reg = registry if registry is not None else get_registry()
        return cls.from_snapshot(reg.snapshot())

    @classmethod
    def from_snapshot(cls, snap: dict) -> "PipelineAttribution":
        samples: List[Tuple[str, Dict[str, str], float]] = []
        for name, fam in snap.items():
            if not isinstance(fam, dict):
                continue
            kind = fam.get("type")
            series = fam.get("series")
            # self-describing riders ({"type": "slo"/"programs"/
            # "pipeline"}) are not metric families
            if kind not in ("counter", "gauge", "histogram") or not (
                isinstance(series, list)
            ):
                continue
            for s in series:
                labels = dict(s.get("labels") or {})
                if kind == "histogram":
                    samples.append(
                        (f"{name}_sum", labels, float(s.get("sum", 0.0)))
                    )
                    samples.append(
                        (
                            f"{name}_count",
                            labels,
                            float(s.get("count", 0.0)),
                        )
                    )
                else:
                    samples.append(
                        (name, labels, float(s.get("value", 0.0)))
                    )
        return cls(samples)

    @classmethod
    def from_prometheus(
        cls, text: str, *, instance: Optional[str] = None
    ) -> "PipelineAttribution":
        """Raises ``ValueError`` on a malformed exposition (the doctor
        maps it to the usage exit)."""
        parsed = parse_prometheus_text(text)
        samples = [
            (name, labels, value)
            for name, labels, value in parsed["samples"]
            if instance is None or labels.get("instance") == instance
        ]
        return cls(samples)

    # -- sample queries ----------------------------------------------------

    def _sum(self, name: str, **want: str) -> float:
        total = 0.0
        for sname, labels, value in self._samples:
            if sname != name:
                continue
            if any(labels.get(k) != v for k, v in want.items()):
                continue
            total += value
        return total

    def _sum_label_prefix(self, name: str, label: str, prefix: str) -> float:
        total = 0.0
        for sname, labels, value in self._samples:
            if sname == name and str(labels.get(label, "")).startswith(
                prefix
            ):
                total += value
        return total

    def _gauge_max(self, name: str) -> Optional[float]:
        vals = [
            value for sname, _, value in self._samples if sname == name
        ]
        return max(vals) if vals else None

    # -- the verdict -------------------------------------------------------

    def attribution(self) -> dict:
        wall = self._sum(f"{STEP_WALL_METRIC}_sum")
        steps = self._sum(f"{STEP_WALL_METRIC}_count")
        stages = {
            s: self._sum(f"{STAGE_METRIC}_sum", stage=s)
            for s in (
                STAGE_FETCH, STAGE_TRANSFORM, STAGE_H2D, STAGE_ENQUEUE
            )
        }
        out: dict = {
            "type": "pipeline",
            "steps": int(steps),
            "wall_seconds": round(wall, 6),
            "stages": {k: round(v, 6) for k, v in stages.items()},
            "queue_full_stalls": int(self._sum(QUEUE_FULL_METRIC)),
            "h2d_bytes_per_second": self._bandwidth(stages),
        }
        if steps <= 0 or wall <= 0:
            out.update(
                {
                    "fractions": {},
                    "bottleneck": None,
                    "verdict": "no-data",
                    "confidence": "none",
                    "margin": 0.0,
                    "input_bound_frac": 0.0,
                    "suggestion": (
                        "no znicz_train_step_wall_seconds samples in this "
                        "capture — run a stepwise training window first"
                    ),
                }
            )
            return out

        wait = min(self._sum(f"{WAIT_METRIC}_sum"), wall)
        wait_count = self._sum(f"{WAIT_METRIC}_count")
        compute = min(
            self._sum_label_prefix(f"{PHASE_METRIC}_sum", "phase", "dispatch/"),
            wall,
        )
        h2d_raw = stages[STAGE_H2D]
        if wait_count > 0:
            # prefetch thread on: while the consumer waits, the producer
            # is in one of its stages — prorate the wait slice by the
            # producer's h2d share of busy (non-enqueue) time
            busy = (
                stages[STAGE_FETCH] + stages[STAGE_TRANSFORM] + h2d_raw
            )
            h2d_frac = (
                (wait / wall) * (h2d_raw / busy) if busy > 0 else 0.0
            )
            wait_frac = max(wait / wall - h2d_frac, 0.0)
        else:
            # no prefetch thread: the probe ran inline on the consumer,
            # its wall sits in the residual outside the dispatch phases
            h2d_frac = min(h2d_raw, max(wall - compute, 0.0)) / wall
            wait_frac = 0.0
        compute_frac = compute / wall
        measured = compute_frac + wait_frac + h2d_frac
        if measured > 1.0:
            # phase/wait timers overlap the wall by jitter: renormalize
            # so the reported fractions stay a partition of 1
            compute_frac /= measured
            wait_frac /= measured
            h2d_frac /= measured
            measured = 1.0
        other_frac = max(1.0 - measured, 0.0)
        fractions = {
            "compute": round(compute_frac, 4),
            "prefetch_wait": round(wait_frac, 4),
            "h2d": round(h2d_frac, 4),
            "other": round(other_frac, 4),
        }
        by_bottleneck = {
            "compute": compute_frac,
            "input": wait_frac,
            "h2d": h2d_frac,
            "other": other_frac,
        }
        ranked = sorted(
            by_bottleneck.items(), key=lambda kv: -kv[1]
        )
        top, top_frac = ranked[0]
        margin = top_frac - ranked[1][1]
        band = min(0.5, 1.0 / math.sqrt(steps))
        if steps >= 20 and margin >= 2 * band:
            confidence = "high"
        elif steps >= 8 and margin >= band:
            confidence = "medium"
        else:
            confidence = "low"
        out.update(
            {
                "fractions": fractions,
                "fractions_sum": round(sum(fractions.values()), 4),
                "bottleneck": top,
                "verdict": _VERDICTS[top],
                "confidence": confidence,
                "margin": round(margin, 4),
                "confidence_band": [
                    round(max(top_frac - band, 0.0), 4),
                    round(min(top_frac + band, 1.0), 4),
                ],
                "input_bound_frac": round(wait_frac + h2d_frac, 4),
                "suggestion": _SUGGESTIONS[top],
            }
        )
        return out

    def _bandwidth(self, stages: Dict[str, float]) -> Optional[float]:
        """Window-consistent first: bytes / h2d-stage seconds — both
        zeroed together by :func:`reset_window`, so the headline never
        blends the compile epoch back in.  The live gauge (a rolling
        probe window reset_window cannot reach) is only the fallback
        for captures without the counter."""
        total = self._sum(H2D_BYTES_METRIC)
        if total > 0 and stages.get(STAGE_H2D, 0.0) > 0:
            return round(total / stages[STAGE_H2D], 1)
        live = self._gauge_max(H2D_BPS_METRIC)
        if live:
            return round(live, 1)
        return None

    def anomaly_summary(self) -> dict:
        """The anomaly view of the same capture: active flag, per-type
        counts and the last loss/grad-norm gauges — what the doctor's
        exit-1 gate reads (the full ring lives in ``status.json``)."""
        active = self._gauge_max(ANOMALY_ACTIVE_METRIC)
        counts: Dict[str, float] = {}
        for name, labels, value in self._samples:
            if name == ANOMALY_TOTAL_METRIC and value > 0:
                key = labels.get("type", "unknown")
                counts[key] = counts.get(key, 0.0) + value
        return {
            "active": bool(active),
            "counts": {k: int(v) for k, v in sorted(counts.items())},
            "total": int(sum(counts.values())),
            "last_loss": self._gauge_max(LAST_LOSS_METRIC),
            "last_grad_norm": self._gauge_max(LAST_GRAD_METRIC),
        }

    def recovery_summary(self) -> dict:
        """The self-healing view of the same capture: rollback /
        restart / loader-retry counters plus the give-up signals
        ``znicz-doctor`` gates on.  ``looping`` is True when the run
        has burned its whole restart budget (the supervisor is about
        to — or already did — give up) or a rollback gave up: both are
        "this run is not healing itself" incidents, the doctor's
        exit-1 condition."""
        rollbacks: Dict[str, int] = {}
        for name, labels, value in self._samples:
            if name == ROLLBACKS_METRIC and value > 0:
                key = labels.get("reason", "unknown")
                rollbacks[key] = rollbacks.get(key, 0) + int(value)
        restarts = int(self._sum(RESTARTS_METRIC))
        budget = self._gauge_max(RESTART_BUDGET_METRIC)
        give_up = bool(self._gauge_max(ROLLBACK_GIVE_UP_METRIC))
        looping = give_up or (
            budget is not None and budget > 0 and restarts >= budget
        )
        return {
            "rollbacks": dict(sorted(rollbacks.items())),
            "rollbacks_total": sum(rollbacks.values()),
            "rollback_give_up": give_up,
            "restarts": restarts,
            "restart_budget": int(budget) if budget is not None else None,
            "loader_retries": int(self._sum(LOADER_RETRIES_METRIC)),
            "loader_skipped_batches": int(
                self._sum(LOADER_SKIPPED_METRIC)
            ),
            "snapshot_failures": int(
                self._sum(SNAPSHOT_FAILURES_METRIC)
            ),
            "looping": looping,
        }
