"""Span tracer: nested host-side spans as Chrome trace-event JSONL.

Complements :func:`znicz_tpu.utils.profiling.trace` (the jax profiler's
device capture): this tracer records the HOST side — admit/decode
chunks, training phases, loader waits — as Chrome trace events that
Perfetto (https://ui.perfetto.dev) renders on a timeline.  When jax is
importable, every span also enters ``jax.profiler.TraceAnnotation``, so
a simultaneous device capture shows the same span names on the device
tracks and host spans line up with the XLA executions they dispatched.

Events are complete spans (``"ph": "X"``) with microsecond ``ts``/
``dur`` relative to :meth:`Tracer.start`, one JSON object per line when
streaming to a file (Perfetto's JSON importer accepts concatenated
objects; the array wrapper is optional in the trace-event format).
Spans are no-ops while the tracer is not recording, so instrumentation
stays in place permanently at ~zero cost.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from collections import Counter
from typing import Dict, Iterator, List, Optional

logger = logging.getLogger(__name__)

_UNSET = object()


class Tracer:
    """Nested host-span recorder with Chrome trace-event export.

    Usage::

        tracer = observability.get_tracer()
        tracer.start(path="/tmp/run.trace.jsonl")  # stream as JSONL
        with tracer.span("epoch", n=3):
            with tracer.span("dispatch/train"):
                ...
        events = tracer.stop()
    """

    def __init__(self, *, max_events: int = 1_000_000):
        self._lock = threading.Lock()
        self._local = threading.local()
        self._events: List[dict] = []
        self._recording = False
        self._file = None
        self._t0 = time.perf_counter()
        self._max_events = max_events
        self.dropped = 0
        self._annotation = _UNSET

    @property
    def recording(self) -> bool:
        return self._recording

    def start(self, path: Optional[str] = None) -> None:
        """Begin recording (optionally streaming each event to ``path``
        as one JSON object per line).  Clears any previous events."""
        with self._lock:
            if self._recording:
                raise RuntimeError("tracer is already recording")
            self._events = []
            self.dropped = 0
            self._t0 = time.perf_counter()
            self._file = open(path, "w") if path else None
            self._recording = True

    def stop(self) -> List[dict]:
        """Stop recording; returns (and keeps) the event list.  When the
        in-memory buffer overflowed, says so — the streamed JSONL file
        (if any) is still complete."""
        with self._lock:
            self._recording = False
            if self._file is not None:
                self._file.close()
                self._file = None
            if self.dropped:
                logger.warning(
                    "tracer buffer dropped %d events past max_events=%d;"
                    " the streamed JSONL file (if any) is complete",
                    self.dropped,
                    self._max_events,
                )
            return list(self._events)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def span_counts(self) -> Counter:
        """Span-name -> completed-span count (the acceptance
        cross-check: N requests => N ``serve/admit`` spans)."""
        return Counter(
            e["name"] for e in self.events() if e.get("ph") == "X"
        )

    def write_jsonl(self, path: str) -> None:
        """Dump the buffered events, one JSON object per line."""
        with self._lock:
            events = list(self._events)
        with open(path, "w") as f:
            for ev in events:
                f.write(json.dumps(ev, separators=(",", ":")) + "\n")

    # -- emission ----------------------------------------------------------

    def _emit(self, ev: dict) -> None:
        with self._lock:
            if not self._recording:
                return  # span outlived a stop(): drop, don't corrupt
            # the file streams EVERY event (disk is the durable record);
            # only the in-memory buffer is capped
            if self._file is not None:
                self._file.write(
                    json.dumps(ev, separators=(",", ":")) + "\n"
                )
            if len(self._events) >= self._max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def _annotation_cls(self):
        """``jax.profiler.TraceAnnotation`` when jax is importable, else
        None — resolved once, lazily, so this module stays jax-free for
        hosts with no accelerator stack."""
        if self._annotation is _UNSET:
            try:
                from jax.profiler import TraceAnnotation

                self._annotation = TraceAnnotation
            except Exception:
                logger.debug(
                    "jax TraceAnnotation unavailable; host spans only",
                    exc_info=True,
                )
                self._annotation = None
        return self._annotation

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[None]:
        """One nested host span; ``args`` land in the event's ``args``.

        Inside a recording window the span also enters
        ``jax.profiler.TraceAnnotation(name)`` so device traces captured
        concurrently (``profiling.trace``) carry the same names."""
        if not self._recording:
            yield
            return
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        parent = stack[-1] if stack else None
        stack.append(name)
        ann = self._annotation_cls()
        ctx = ann(name) if ann is not None else contextlib.nullcontext()
        t0 = time.perf_counter()
        try:
            with ctx:
                yield
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            a: Dict[str, object] = dict(args)
            if parent is not None:
                a["parent"] = parent
            ev = {
                "name": name,
                "ph": "X",
                "cat": "host",
                "ts": round((t0 - self._t0) * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "pid": os.getpid(),
                "tid": threading.get_ident(),
            }
            if a:
                ev["args"] = a
            self._emit(ev)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker event (``"ph": "i"``)."""
        if not self._recording:
            return
        ev = {
            "name": name,
            "ph": "i",
            "s": "t",
            "cat": "host",
            "ts": round((time.perf_counter() - self._t0) * 1e6, 3),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        self._emit(ev)


_DEFAULT = Tracer()


def get_tracer() -> Tracer:
    """The process-wide default tracer every subsystem's spans feed."""
    return _DEFAULT


def span(name: str, **args):
    return _DEFAULT.span(name, **args)


def instant(name: str, **args) -> None:
    _DEFAULT.instant(name, **args)
