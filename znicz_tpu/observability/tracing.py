"""Span tracer: nested host-side spans as Chrome trace-event JSONL.

Complements :func:`znicz_tpu.utils.profiling.trace` (the jax profiler's
device capture): this tracer records the HOST side — admit/decode
chunks, training phases, loader waits — as Chrome trace events that
Perfetto (https://ui.perfetto.dev) renders on a timeline.  When jax is
importable, every span also enters ``jax.profiler.TraceAnnotation``, so
a simultaneous device capture shows the same span names on the device
tracks and host spans line up with the XLA executions they dispatched.

Events are complete spans (``"ph": "X"``) with microsecond ``ts``/
``dur`` relative to :meth:`Tracer.start`, one JSON object per line when
streaming to a file (Perfetto's JSON importer accepts concatenated
objects; the array wrapper is optional in the trace-event format).
Spans are no-ops while the tracer is not recording, so instrumentation
stays in place permanently at ~zero cost.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from collections import Counter
from typing import Dict, Iterator, List, Optional

logger = logging.getLogger(__name__)

_UNSET = object()

# default size cap for a STREAMED trace file: on a long-running server
# the stream is otherwise unbounded (the in-memory buffer is capped,
# the file deliberately is not truncated — so it must rotate instead)
TRACE_FILE_MAX_BYTES = 256 * 1024 * 1024


class Tracer:
    """Nested host-span recorder with Chrome trace-event export.

    Usage::

        tracer = observability.get_tracer()
        tracer.start(path="/tmp/run.trace.jsonl")  # stream as JSONL
        with tracer.span("epoch", n=3):
            with tracer.span("dispatch/train"):
                ...
        events = tracer.stop()
    """

    def __init__(self, *, max_events: int = 1_000_000):
        self._lock = threading.Lock()
        self._local = threading.local()
        self._events: List[dict] = []
        self._recording = False
        self._file = None
        self._path: Optional[str] = None
        self._file_bytes = 0
        self._max_file_bytes = 0
        self._t0 = time.perf_counter()
        # wall-clock twin of _t0: the collector rebases instances onto
        # one shared timeline by epoch difference (cross-process clock
        # alignment is exactly what wall clock is for)
        self.epoch_us = time.time() * 1e6
        self._max_events = max_events
        self.dropped = 0
        self.rotations = 0
        self._annotation = _UNSET
        # fleet tracing: the default instance tag every emitted event
        # carries (pid=instance in the collector's merged view), and
        # bounded sinks a TracePusher drains span batches from
        self.instance: Optional[str] = None
        self._sinks: List = []

    @property
    def recording(self) -> bool:
        return self._recording

    def set_instance(self, instance: Optional[str]) -> None:
        """Default ``instance`` tag stamped into every emitted event's
        args (explicit per-span ``instance=...`` args win).  The
        fleet trace collector groups the merged timeline by this tag —
        one process serving several logical instances (an in-process
        test fleet) tags per-span instead."""
        with self._lock:
            self.instance = instance

    def add_sink(self, maxlen: int = 65536):
        """Register a BOUNDED event sink (a deque): every emitted event
        is appended, oldest dropped past ``maxlen`` — the TracePusher's
        intake.  Returns the deque; detach with :meth:`remove_sink`."""
        from collections import deque

        q = deque(maxlen=int(maxlen))
        with self._lock:
            self._sinks.append(q)
        return q

    def remove_sink(self, q) -> None:
        with self._lock:
            if q in self._sinks:
                self._sinks.remove(q)

    def ensure_recording(self) -> bool:
        """Start a buffer-only recording window if none is active (the
        front door's collector wiring calls this so spans flow without
        the operator having to start the tracer by hand).  True when
        THIS call started it."""
        with self._lock:
            if self._recording:
                return False
        try:
            self.start()
        except RuntimeError:
            return False  # lost the race: someone else just started it
        return True

    def start(
        self,
        path: Optional[str] = None,
        *,
        max_file_bytes: Optional[int] = TRACE_FILE_MAX_BYTES,
    ) -> None:
        """Begin recording (optionally streaming each event to ``path``
        as one JSON object per line).  Clears any previous events.

        The streamed file is SIZE-CAPPED at ``max_file_bytes``
        (``None``/``0`` disables): when a write would cross the cap the
        file rotates — the current file becomes ``<path>.1``
        (overwriting any previous rotation) and streaming continues
        into a fresh ``<path>`` — so a long-running server keeps at
        most ~two caps of trace on disk, newest window always in
        ``<path>``."""
        with self._lock:
            if self._recording:
                raise RuntimeError("tracer is already recording")
            self._events = []
            self.dropped = 0
            self.rotations = 0
            self._t0 = time.perf_counter()
            self.epoch_us = time.time() * 1e6  # wall twin of _t0
            self._path = path
            # znicz-check: disable=ZNC016 -- one-time start(): the
            # handle IS the lock-guarded state; a local open-for-write
            # is bounded and racing it against span() would lose events
            self._file = (
                open(path, "w")  # znicz-check: disable=ZNC016
                if path
                else None
            )
            self._file_bytes = 0
            self._max_file_bytes = int(max_file_bytes or 0)
            self._recording = True

    def stop(self) -> List[dict]:
        """Stop recording; returns (and keeps) the event list.  When the
        in-memory buffer overflowed, says so — the streamed JSONL file
        (if any) holds every event since its last rotation (older
        generations beyond ``<path>.1`` rotate away)."""
        with self._lock:
            self._recording = False
            if self._file is not None:
                self._file.close()
                self._file = None
            if self.dropped:
                logger.warning(
                    "tracer buffer dropped %d events past max_events=%d;"
                    " the streamed JSONL file (if any) is complete back"
                    " to its last rotation (%d rotations)",
                    self.dropped,
                    self._max_events,
                    self.rotations,
                )
            return list(self._events)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def span_counts(self) -> Counter:
        """Span-name -> completed-span count (the acceptance
        cross-check: N requests => N ``serve/admit`` spans)."""
        return Counter(
            e["name"] for e in self.events() if e.get("ph") == "X"
        )

    def write_jsonl(self, path: str) -> None:
        """Dump the buffered events, one JSON object per line."""
        with self._lock:
            events = list(self._events)
        with open(path, "w") as f:
            for ev in events:
                f.write(json.dumps(ev, separators=(",", ":")) + "\n")

    # -- emission ----------------------------------------------------------

    def _emit(self, ev: dict) -> None:
        with self._lock:
            if not self._recording:
                return  # span outlived a stop(): drop, don't corrupt
            if self.instance is not None:
                # default instance tag (explicit per-span args win):
                # the fleet collector's pid=instance grouping key
                args = ev.setdefault("args", {})
                args.setdefault("instance", self.instance)
            for q in self._sinks:
                q.append(ev)  # bounded: deque maxlen drops the oldest
            # the file streams EVERY event (disk is the durable record);
            # only the in-memory buffer is capped — the file instead
            # ROTATES at max_file_bytes so a long-running server's
            # trace stays bounded without losing the newest window
            if self._file is not None:
                # ensure_ascii JSON is pure ASCII, so len(line) IS the
                # on-disk byte count the rotation cap accounts against
                line = json.dumps(ev, separators=(",", ":")) + "\n"
                if (
                    self._max_file_bytes
                    and self._file_bytes
                    and self._file_bytes + len(line) > self._max_file_bytes
                ):
                    # znicz-check: disable=ZNC016 -- rotation must be
                    # atomic with the stream (the handle is the guarded
                    # state); rename+reopen on a local FS is bounded and
                    # fires once per max_file_bytes of trace
                    self._rotate_locked()  # znicz-check: disable=ZNC016
                if self._file is not None:
                    # a doubly-failed rotation (rename AND reopen) drops
                    # the stream: memory-buffer-only from here
                    self._file.write(line)
                    self._file_bytes += len(line)
            if len(self._events) >= self._max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def _rotate_locked(self) -> None:
        """Close the streamed file, shift it to ``<path>.1`` and reopen
        ``<path>`` (lock held by the caller).  A failed rename keeps
        streaming into the grown file — rotation is best-effort, the
        trace must never take the server down."""
        try:
            self._file.close()
            os.replace(self._path, self._path + ".1")
            self._file = open(self._path, "w")
            self._file_bytes = 0
            self.rotations += 1
        except OSError:
            logger.warning(
                "trace rotation of %s failed; stream continues uncapped",
                self._path, exc_info=True,
            )
            self._max_file_bytes = 0
            if self._file.closed:  # reopen in append: keep streaming
                try:
                    self._file = open(self._path, "a")
                except OSError:
                    # the path itself is gone (dir deleted, EROFS):
                    # degrade to the in-memory buffer — the trace must
                    # never take the instrumented thread down
                    logger.warning(
                        "trace stream %s lost; buffering in memory only",
                        self._path, exc_info=True,
                    )
                    self._file = None

    def _annotation_cls(self):
        """``jax.profiler.TraceAnnotation`` when jax is importable, else
        None — resolved once, lazily, so this module stays jax-free for
        hosts with no accelerator stack."""
        if self._annotation is _UNSET:
            try:
                from jax.profiler import TraceAnnotation

                self._annotation = TraceAnnotation
            except Exception:
                logger.debug(
                    "jax TraceAnnotation unavailable; host spans only",
                    exc_info=True,
                )
                self._annotation = None
        return self._annotation

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[None]:
        """One nested host span; ``args`` land in the event's ``args``.

        Inside a recording window the span also enters
        ``jax.profiler.TraceAnnotation(name)`` so device traces captured
        concurrently (``profiling.trace``) carry the same names."""
        if not self._recording:
            yield
            return
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        parent = stack[-1] if stack else None
        stack.append(name)
        ann = self._annotation_cls()
        ctx = ann(name) if ann is not None else contextlib.nullcontext()
        t0 = time.perf_counter()
        try:
            with ctx:
                yield
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            a: Dict[str, object] = dict(args)
            if parent is not None:
                a["parent"] = parent
            ev = {
                "name": name,
                "ph": "X",
                "cat": "host",
                "ts": round((t0 - self._t0) * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "pid": os.getpid(),
                "tid": threading.get_ident(),
            }
            if a:
                ev["args"] = a
            self._emit(ev)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker event (``"ph": "i"``)."""
        if not self._recording:
            return
        ev = {
            "name": name,
            "ph": "i",
            "s": "t",
            "cat": "host",
            "ts": round((time.perf_counter() - self._t0) * 1e6, 3),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        self._emit(ev)


_DEFAULT = Tracer()


def get_tracer() -> Tracer:
    """The process-wide default tracer every subsystem's spans feed."""
    return _DEFAULT


def span(name: str, **args):
    return _DEFAULT.span(name, **args)


def instant(name: str, **args) -> None:
    _DEFAULT.instant(name, **args)
