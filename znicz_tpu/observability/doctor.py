"""znicz-doctor: training triage over one metrics capture.

The gate the streaming-rebuild rung is judged with: point it at a
metrics source and it prints the pipeline-attribution verdict plus the
anomaly state, e.g. ::

    $ tools/znicz-doctor run/metrics.prom
    input-bound: 0.83 of step wall in prefetch-wait (compute 0.12,
      h2d 0.03, other 0.02); H2D ~12.0 MB/s; confidence high, 64 steps
    anomalies: none
    suggest: raise prefetch depth, shard loaders across processes, ...

Sources (same contract as ``tools/znicz-slo``): a local
``metrics.prom`` path, or an http(s) URL — a serving replica's or the
aggregator's ``/metrics`` (a bare ``http://host:port`` gets
``/metrics`` appended).  On a fleet exposition pass ``--instance`` to
scope the attribution to one process's series.

Exit codes: **0** healthy (including "no training data in this
capture" — absence of evidence is not an incident), **1** an anomaly
is ACTIVE (``znicz_train_anomaly_active`` > 0 — the flight recorder
fired within its active window; the ring itself lives in
``status.json``) OR the run is **restart-looping** (its supervised
restart budget is spent — ``znicz_train_restarts_total`` >=
``znicz_train_restart_budget`` — or a rollback gave up:
``znicz_train_rollback_give_up``), **2** usage / unreadable source /
malformed exposition — the ``tools/znicz-bench-diff`` convention.
The self-healing counters (rollbacks by reason, restarts, loader
retries/skips, snapshot write failures) print on their own line and
ride the ``--json`` output as ``"recovery"``.
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

from znicz_tpu.observability.pipeline import PipelineAttribution
from znicz_tpu.observability.slo import _read_source

USAGE = (
    "usage: znicz-doctor <metrics.prom | http://host:port[/metrics]> "
    "[--instance NAME] [--json]"
)


def _fmt_bandwidth(bps: Optional[float]) -> str:
    if bps is None:
        return "H2D n/a"
    return f"H2D ~{bps / 1e6:.1f} MB/s"


def _render_recovery(rec: dict) -> List[str]:
    """The self-healing line(s): silent when nothing ever fired."""
    lines: List[str] = []
    parts: List[str] = []
    if rec["rollbacks_total"]:
        by_reason = ", ".join(
            f"{k}={v}" for k, v in rec["rollbacks"].items()
        )
        parts.append(f"rollbacks {rec['rollbacks_total']} ({by_reason})")
    if rec["restarts"]:
        budget = (
            f"/{rec['restart_budget']}"
            if rec["restart_budget"] is not None
            else ""
        )
        parts.append(f"restarts {rec['restarts']}{budget}")
    if rec["loader_retries"]:
        parts.append(f"loader retries {rec['loader_retries']}")
    if rec["loader_skipped_batches"]:
        parts.append(
            f"skipped batches {rec['loader_skipped_batches']}"
        )
    if rec["snapshot_failures"]:
        parts.append(f"snapshot failures {rec['snapshot_failures']}")
    if parts:
        lines.append("self-healing: " + "; ".join(parts))
    if rec["looping"]:
        why = (
            "rollback gave up"
            if rec["rollback_give_up"]
            else "restart budget spent"
        )
        lines.append(
            f"self-healing: LOOPING ({why}) — this run is not healing "
            "itself; intervene"
        )
    return lines


def _render(att: dict, anomalies: dict, recovery: dict) -> str:
    lines: List[str] = []
    if att["verdict"] == "no-data":
        lines.append(
            "no-data: no training step-wall samples in this capture"
        )
    else:
        f = att["fractions"]
        others = ", ".join(
            f"{k} {f[k]:.2f}"
            for k in ("compute", "prefetch_wait", "h2d", "other")
            if k != _headline_key(att["bottleneck"])
        )
        lines.append(
            f"{att['verdict']}: {f[_headline_key(att['bottleneck'])]:.2f} "
            f"of step wall in {_headline_name(att['bottleneck'])} "
            f"({others}); {_fmt_bandwidth(att['h2d_bytes_per_second'])}; "
            f"confidence {att['confidence']}, {att['steps']} steps"
        )
        if att["queue_full_stalls"]:
            lines.append(
                f"prefetch depth exhausted {att['queue_full_stalls']} "
                "time(s): the producer outran the consumer — the "
                "input pipeline is keeping up"
            )
    if anomalies["active"]:
        counts = ", ".join(
            f"{k}={v}" for k, v in anomalies["counts"].items()
        )
        lines.append(
            f"anomalies: ACTIVE ({counts or 'unknown'}; "
            f"{anomalies['total']} total) — see status.json for the "
            "flight-recorder ring"
        )
    elif anomalies["total"]:
        counts = ", ".join(
            f"{k}={v}" for k, v in anomalies["counts"].items()
        )
        lines.append(
            f"anomalies: none active ({counts}; past incidents only)"
        )
    else:
        lines.append("anomalies: none")
    lines.extend(_render_recovery(recovery))
    if att.get("suggestion"):
        lines.append(f"suggest: {att['suggestion']}")
    return "\n".join(lines)


def _headline_key(bottleneck: str) -> str:
    return {"input": "prefetch_wait"}.get(bottleneck, bottleneck)


def _headline_name(bottleneck: str) -> str:
    return {
        "input": "prefetch-wait",
        "h2d": "host->device transfer",
        "compute": "device compute/dispatch",
        "other": "untimed host work",
    }[bottleneck]


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    if as_json:
        args.remove("--json")
    instance = None
    if "--instance" in args:
        i = args.index("--instance")
        if i + 1 >= len(args):
            print("--instance needs a value", file=sys.stderr)
            return 2
        instance = args[i + 1]
        del args[i:i + 2]
    if len(args) != 1 or args[0].startswith("--"):
        print(USAGE, file=sys.stderr)
        return 2
    try:
        text = _read_source(args[0])
        att_src = PipelineAttribution.from_prometheus(
            text, instance=instance
        )
        att = att_src.attribution()
        anomalies = att_src.anomaly_summary()
        recovery = att_src.recovery_summary()
    except (OSError, ValueError) as exc:
        print(f"znicz-doctor: {exc}", file=sys.stderr)
        return 2
    if as_json:
        print(
            json.dumps(
                {
                    "source": args[0],
                    "instance": instance,
                    **att,
                    "anomalies": anomalies,
                    "recovery": recovery,
                }
            )
        )
    else:
        print(_render(att, anomalies, recovery))
    return 1 if anomalies["active"] or recovery["looping"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
