"""Fleet trace collection: spans push, the master merges the timeline.

PR 7 gave the fleet ONE metrics view (:mod:`aggregate`); traces stayed
per-process — the router, each replica and the engine each stream their
own Chrome-trace file with no shared clock, so following one request
across a failover meant eyeballing three files.  This module is the
tracing twin of the aggregator, same master–slave shape (SURVEY §3.4):

* :class:`TracePusher` — the slave side: drains a BOUNDED sink off the
  process tracer and POSTs span batches to a collector every
  ``interval_s``; every call timeout-bounded, failures counted and
  logged but NEVER raised (a dead collector must not hurt serving),
  final flush on :meth:`TracePusher.stop`.  Fault-injectable at
  ``trace_pusher.push``.
* :class:`TraceCollector` — holds the latest span window per instance
  (bounded per-instance ring; each push carries its own TTL, stale
  instances expire out of the merged view) and merges live instances
  into ONE Perfetto-loadable Chrome trace: ``GET /trace`` returns
  ``{"traceEvents": [...]}`` with **pid = instance** (a
  ``process_name`` metadata event per instance) and every instance's
  timestamps REBASED onto a shared wall-clock epoch — so a single
  trace-id filter shows a request's full life across the router hop,
  replica queue/prefill/decode, failover re-routes and preemptions.
* :func:`build_collector_server` — the HTTP surface: ``POST /push``,
  ``GET /trace`` (``?trace_id=`` filters server-side), ``GET
  /instances`` (who is pushing, how stale), ``GET /healthz``.

Instance attribution is per-EVENT first: an event whose ``args``
carry an ``instance`` tag (the engine/front door/router stamp their
spans; :meth:`~znicz_tpu.observability.tracing.Tracer.set_instance`
sets a process default) groups under that tag; untagged events fall
back to the push envelope's instance.  One process hosting several
logical instances (an in-process test fleet, a router beside a
replica) therefore still splits into per-instance tracks.

Pure stdlib, like the rest of :mod:`znicz_tpu.observability`.
"""

from __future__ import annotations

import http.client
import http.server
import json
import logging
import os
import socket
import threading
import time
import urllib.parse
from collections import deque
from typing import Dict, List, Optional

from znicz_tpu.observability.registry import get_registry
from znicz_tpu.observability.tracing import Tracer, get_tracer
from znicz_tpu.utils import faults

logger = logging.getLogger(__name__)

# per-instance span window: big enough for minutes of serving traffic,
# small enough that a runaway pusher cannot OOM the collector
DEFAULT_MAX_EVENTS_PER_INSTANCE = 200_000


class _TraceInstance:
    __slots__ = (
        "events", "pushed_at", "ttl_s", "pushes", "epoch_us", "dropped"
    )

    def __init__(self, maxlen: int, ttl_s: float, now: float):
        self.events: deque = deque(maxlen=maxlen)
        self.pushed_at = now
        self.ttl_s = ttl_s
        self.pushes = 0
        self.epoch_us: Optional[float] = None
        self.dropped = 0


def _id_matches(value, trace_id: str) -> bool:
    """Exact id match, plus the front door's live-collision spelling:
    a duplicate inbound id is adopted as ``<id>-r<digits>``
    (``ServingFrontDoor._mint_id``), and the filter must keep that
    request's lifecycle visible under the client's original id.  The
    suffix must be all digits — a DIFFERENT client-chosen id that
    merely starts with ``<id>-r`` (``batch`` vs ``batch-run2``) must
    not pollute the filtered timeline."""
    if value == trace_id:
        return True
    if not isinstance(value, str) or not value.startswith(
        trace_id + "-r"
    ):
        return False
    suffix = value[len(trace_id) + 2:]
    return bool(suffix) and suffix.isdigit()


def _event_matches(ev: dict, trace_id: str) -> bool:
    """One trace-id filter over the span-arg conventions the repo
    emits: engine spans carry ``trace``, front-door instants ``id``,
    batched decode chunks a comma-joined ``traces`` list."""
    args = ev.get("args") or {}
    if _id_matches(args.get("trace"), trace_id) or _id_matches(
        args.get("id"), trace_id
    ):
        return True
    traces = args.get("traces")
    return isinstance(traces, str) and any(
        _id_matches(tok, trace_id) for tok in traces.split(",")
    )


class TraceCollector:
    """Thread-safe per-instance span store with a merged fleet trace.

    Each push APPENDS to that instance's bounded event window (spans
    are deltas, unlike registry snapshots — the latest push is NOT the
    whole story) and refreshes its TTL; an instance whose TTL lapses
    silently leaves the merged view.  ``epoch_us`` (wall-clock of the
    pushing tracer's ``ts=0``) rides the envelope so instances land on
    one shared timeline."""

    def __init__(
        self,
        *,
        default_ttl_s: float = 60.0,
        max_events_per_instance: int = DEFAULT_MAX_EVENTS_PER_INSTANCE,
    ):
        if default_ttl_s <= 0:
            raise ValueError(
                f"want default_ttl_s > 0; got {default_ttl_s}"
            )
        if max_events_per_instance < 1:
            raise ValueError(
                "want max_events_per_instance >= 1; got "
                f"{max_events_per_instance}"
            )
        self.default_ttl_s = float(default_ttl_s)
        self.max_events_per_instance = int(max_events_per_instance)
        self._lock = threading.Lock()
        self._instances: Dict[str, _TraceInstance] = {}
        self._n_pushes = 0
        reg = get_registry()
        self._m_pushes = reg.counter(
            "znicz_trace_collector_pushes_total",
            "span-batch pushes accepted by this collector",
        )
        self._m_events = reg.counter(
            "znicz_trace_collector_events_total",
            "span events accepted by this collector",
        )

    # -- intake ------------------------------------------------------------

    def push(
        self,
        instance: str,
        events: List[dict],
        *,
        ttl_s: Optional[float] = None,
        epoch_us: Optional[float] = None,
        now: Optional[float] = None,
    ) -> int:
        """Record one span batch for ``instance``; returns the events
        accepted.  Raises ``ValueError`` on malformed input (the HTTP
        layer answers 400 — a broken pusher must not poison the merged
        trace)."""
        if not instance:
            raise ValueError("push needs a non-empty instance name")
        if not isinstance(events, list) or any(
            not isinstance(ev, dict) for ev in events
        ):
            raise ValueError("events must be a list of trace-event dicts")
        ttl = float(ttl_s) if ttl_s is not None else self.default_ttl_s
        if ttl <= 0:
            raise ValueError(f"want ttl_s > 0; got {ttl}")
        if epoch_us is not None:
            epoch_us = float(epoch_us)
        t = time.monotonic() if now is None else now
        with self._lock:
            inst = self._instances.get(str(instance))
            if inst is None:
                inst = self._instances[str(instance)] = _TraceInstance(
                    self.max_events_per_instance, ttl, t
                )
            before = len(inst.events)
            inst.events.extend(events)
            overflow = before + len(events) - len(inst.events)
            if overflow > 0:
                inst.dropped += overflow
            inst.pushed_at = t
            inst.ttl_s = ttl
            inst.pushes += 1
            if epoch_us is not None:
                inst.epoch_us = epoch_us
            self._n_pushes += 1
        self._m_pushes.inc()
        self._m_events.inc(len(events))
        return len(events)

    def forget(self, instance: str) -> bool:
        """Drop ``instance`` immediately (orderly shutdown need not
        wait for its TTL)."""
        with self._lock:
            return self._instances.pop(str(instance), None) is not None

    # -- views -------------------------------------------------------------

    def _live(self, now: Optional[float]) -> Dict[str, _TraceInstance]:
        t = time.monotonic() if now is None else now
        with self._lock:
            stale = [
                name for name, inst in self._instances.items()
                if t - inst.pushed_at > inst.ttl_s
            ]
            for name in stale:
                del self._instances[name]
            return dict(self._instances)

    def instances(self, now: Optional[float] = None) -> List[dict]:
        """Live pushers: name, seconds since last push, TTL, push and
        event counts, window drops."""
        t = time.monotonic() if now is None else now
        return [
            {
                "instance": name,
                "age_s": round(t - inst.pushed_at, 3),
                "ttl_s": inst.ttl_s,
                "pushes": inst.pushes,
                "events": len(inst.events),
                "dropped": inst.dropped,
            }
            for name, inst in sorted(self._live(now).items())
        ]

    def merged_trace(
        self,
        trace_id: Optional[str] = None,
        now: Optional[float] = None,
    ) -> dict:
        """ONE Chrome-trace JSON object over every live instance —
        load it straight into Perfetto.  ``pid`` is a stable small int
        per instance tag (``process_name`` metadata names it), ``ts``
        is rebased per instance onto the earliest live epoch so the
        timeline is shared, and ``trace_id`` (when given) filters to
        the spans of one request before the events leave the
        collector."""
        live = self._live(now)
        # copy each window UNDER the lock: a concurrent push()'s
        # extend (which also pops left past maxlen) would otherwise
        # blow up this iteration exactly when the fleet is busiest
        with self._lock:
            windows = {
                name: list(inst.events) for name, inst in live.items()
            }
        epochs = [
            inst.epoch_us for inst in live.values()
            if inst.epoch_us is not None
        ]
        base = min(epochs) if epochs else 0.0
        # pass 1: gather (tag, rebased event) so pid assignment is
        # deterministic (sorted tags), whatever the push order was
        tagged: List = []
        tags = set()
        for name in sorted(live):
            inst = live[name]
            offset = (
                inst.epoch_us - base if inst.epoch_us is not None else 0.0
            )
            for ev in windows[name]:
                if trace_id is not None and not _event_matches(
                    ev, trace_id
                ):
                    continue
                tag = (ev.get("args") or {}).get("instance") or name
                tags.add(tag)
                out = dict(ev)
                if "ts" in out:
                    out["ts"] = round(float(out["ts"]) + offset, 3)
                tagged.append((tag, out))
        pid_of = {tag: i + 1 for i, tag in enumerate(sorted(tags))}
        events = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": tag},
            }
            for tag, pid in sorted(pid_of.items())
        ]
        for tag, ev in tagged:
            ev["pid"] = pid_of[tag]
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "instances": sorted(pid_of),
        }


# -- the HTTP surface -------------------------------------------------------


class CollectorRequestHandler(http.server.BaseHTTPRequestHandler):
    """``POST /push`` + the merged trace endpoints; explicit
    Content-Length on every response (no streaming here)."""

    protocol_version = "HTTP/1.1"
    collector: TraceCollector  # set by build_collector_server

    def log_message(self, fmt, *args):  # noqa: A003 — http.server API
        logger.debug("collector http: " + fmt, *args)

    def do_GET(self):  # noqa: N802 — http.server API
        path, _, query = self.path.partition("?")
        if path == "/trace":
            qs = urllib.parse.parse_qs(query)
            trace_id = qs.get("trace_id", [None])[0]
            self._send_json(self.collector.merged_trace(trace_id))
        elif path == "/instances":
            inst = self.collector.instances()
            self._send_json({"instances": inst, "live": len(inst)})
        elif path == "/healthz":
            self._send(b"ok\n", "text/plain")
        else:
            self._send_json({"error": "unknown endpoint"}, status=404)

    def do_POST(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        if path != "/push":
            self._send_json({"error": "unknown endpoint"}, status=404)
            return
        try:
            n = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("push body must be a JSON object")
            instance = payload.get("instance")
            if not instance:
                raise ValueError("push needs an 'instance' key")
            accepted = self.collector.push(
                instance,
                payload.get("events") or [],
                ttl_s=payload.get("ttl_s"),
                epoch_us=payload.get("epoch_us"),
            )
        except (ValueError, TypeError, UnicodeDecodeError) as exc:
            self._send_json(
                {"error": "bad_push", "detail": str(exc)}, status=400
            )
            return
        self._send_json({"ok": True, "accepted": accepted})

    def _send_json(self, obj: dict, status: int = 200) -> None:
        self._send(
            (json.dumps(obj) + "\n").encode(), "application/json",
            status=status,
        )

    def _send(self, body: bytes, content_type: str, status: int = 200):
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def build_collector_server(
    collector: Optional[TraceCollector] = None,
    port: int = 9110,
    host: str = "127.0.0.1",
) -> http.server.ThreadingHTTPServer:
    """A ready-to-serve trace collector; ``port=0`` binds ephemeral
    (read it back from ``server.server_address``).  The collector is
    reachable as ``server.collector``."""
    col = collector if collector is not None else TraceCollector()
    handler = type(
        "BoundCollectorHandler",
        (CollectorRequestHandler,),
        {"collector": col},
    )
    server = http.server.ThreadingHTTPServer((host, port), handler)
    server.collector = col
    return server


def main(argv=None) -> int:
    """``python -m znicz_tpu.observability.collector [port] [host]`` —
    run a standalone fleet trace collector (loopback by default)."""
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    port = int(args[0]) if args else 9110
    host = args[1] if len(args) > 1 else "127.0.0.1"
    server = build_collector_server(port=port, host=host)
    host, port = server.server_address[:2]
    print(
        f"znicz trace collector on http://{host}:{port} "
        "(push to /push, merged Perfetto trace at /trace)"
    )
    server.serve_forever()
    return 0


# -- the slave side ---------------------------------------------------------


class TracePusher:
    """Background span pusher: drain a bounded sink off ``tracer`` and
    POST span batches to a collector every ``interval_s``, each attempt
    bounded by ``timeout_s`` and advertised with ``ttl_s = ttl_factor *
    interval_s``.  An empty batch still pushes (a keep-alive, so an
    idle instance stays in the merged view).

    Failures never propagate: a dead collector costs one log line and a
    counter tick, not a serving thread; the failed batch is DROPPED
    (spans are diagnostics — redelivery would reorder the timeline).
    :meth:`push_now` is the synchronous hook tests drive; the
    ``trace_pusher.push`` fault point makes the failure path
    deterministic in CI."""

    def __init__(
        self,
        url: str,
        *,
        instance: Optional[str] = None,
        interval_s: float = 2.0,
        tracer: Optional[Tracer] = None,
        timeout_s: float = 5.0,
        ttl_factor: float = 5.0,
        max_batch: int = 5000,
        queue_len: int = 65536,
    ):
        if interval_s <= 0:
            raise ValueError(f"want interval_s > 0; got {interval_s}")
        parsed = urllib.parse.urlsplit(url)
        if parsed.scheme != "http" or not parsed.hostname:
            raise ValueError(
                f"want an http://host:port collector url; got {url!r}"
            )
        self.host = parsed.hostname
        self.port = parsed.port or 80
        base = parsed.path.rstrip("/")
        self.path = base + "/push" if not base.endswith("/push") else base
        self.instance = (
            instance
            if instance
            else f"{socket.gethostname()}-{os.getpid()}"
        )
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.ttl_factor = float(ttl_factor)
        self.ttl_s = self.ttl_factor * self.interval_s
        self.max_batch = int(max_batch)
        self._tracer = tracer if tracer is not None else get_tracer()
        self._queue = self._tracer.add_sink(maxlen=queue_len)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.pushes_ok = 0
        self.pushes_failed = 0
        self._m_pushes = get_registry().counter(
            "znicz_trace_pusher_pushes_total",
            "collector pushes attempted by this process, by outcome",
            ("status",),
        )
        self._m_dropped = get_registry().counter(
            "znicz_trace_pusher_events_dropped_total",
            "span events dropped on failed collector pushes",
        )

    def start(self) -> "TracePusher":
        """Start the background push loop (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        # _loop only calls push_now, whose contract is "never raises"
        # (every failure is caught, counted and logged inside it)
        self._thread = threading.Thread(  # znicz-check: disable=ZNC013
            target=self._loop,
            name=f"znicz-trace-pusher-{self.instance}",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Stop the loop; the thread drains the remaining sink in a
        bounded number of final flush pushes, then the sink detaches
        from the tracer.  The join waits at most ``timeout`` (default:
        push timeout + 2 intervals)."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(
                timeout=(
                    timeout
                    if timeout is not None
                    else self.timeout_s + 2 * self.interval_s
                )
            )
        self._tracer.remove_sink(self._queue)

    def __enter__(self) -> "TracePusher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            self.push_now()
        # final flush: bounded batches, so a huge backlog cannot wedge
        # shutdown — and one keep-alive push even when already empty
        for _ in range(10):
            self.push_now()
            if not self._queue:
                break

    def push_now(self) -> bool:
        """One synchronous, bounded push of up to ``max_batch`` queued
        events; True on 2xx.  Never raises."""
        batch: List[dict] = []
        while self._queue and len(batch) < self.max_batch:
            try:
                batch.append(self._queue.popleft())
            except IndexError:  # znicz-check: disable=ZNC008
                # benign race: the deque drained between the loop's
                # emptiness check and the pop — nothing was lost
                break
        try:
            faults.fire("trace_pusher.push")
            body = json.dumps(
                {
                    "instance": self.instance,
                    "ttl_s": self.ttl_s,
                    "epoch_us": self._tracer.epoch_us,
                    "events": batch,
                }
            ).encode()
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s
            )
            try:
                conn.request(
                    "POST", self.path, body=body,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                resp.read()
                ok = 200 <= resp.status < 300
            finally:
                conn.close()
        except Exception as exc:
            self.pushes_failed += 1
            self._m_pushes.labels(status="error").inc()
            if batch:
                self._m_dropped.inc(len(batch))
            logger.debug(
                "trace push to %s:%s failed: %s",
                self.host, self.port, exc,
            )
            return False
        if ok:
            self.pushes_ok += 1
            self._m_pushes.labels(status="ok").inc()
        else:
            self.pushes_failed += 1
            self._m_pushes.labels(status="error").inc()
            if batch:
                self._m_dropped.inc(len(batch))
            logger.debug(
                "trace push to %s:%s rejected: HTTP %s",
                self.host, self.port, resp.status,
            )
        return ok


# -- process-shared pushers -------------------------------------------------

_SHARED_LOCK = threading.Lock()
_SHARED: Dict[tuple, TracePusher] = {}


def attach_pusher(
    url: str,
    *,
    instance: Optional[str] = None,
    tracer: Optional[Tracer] = None,
    interval_s: float = 2.0,
) -> TracePusher:
    """ONE running pusher per (collector url, tracer) per process,
    however many components attach.  Every sink sees every tracer
    event, so a second :class:`TracePusher` on the same tracer would
    push each span TWICE into the merged view — an in-process fleet
    (two front doors beside a router) must share one pusher, with
    per-event ``instance`` tags keeping the attribution.  The first
    attachment's ``instance`` names the push envelope (the fallback
    tag for untagged events); a later attachment asking for a FASTER
    cadence tightens the shared interval (and its advertised TTL) —
    the pusher runs at the fastest cadence anyone attached with; the
    pusher stops when the LAST attachment calls
    :func:`detach_pusher`."""
    t = tracer if tracer is not None else get_tracer()
    key = (str(url), id(t))
    with _SHARED_LOCK:
        pusher = _SHARED.get(key)
        if pusher is None:
            pusher = TracePusher(
                url, instance=instance, tracer=t, interval_s=interval_s
            )
            pusher._shared_key = key
            pusher._shared_refs = 1
            _SHARED[key] = pusher
            pusher.start()
        else:
            pusher._shared_refs += 1
            if float(interval_s) < pusher.interval_s:
                # applied on the loop's next wait; TTL scales with it
                pusher.interval_s = float(interval_s)
                pusher.ttl_s = pusher.ttl_factor * pusher.interval_s
                logger.debug(
                    "shared trace pusher %s tightened to %.2fs by a "
                    "later attachment", key[0], pusher.interval_s,
                )
        return pusher


def detach_pusher(pusher: TracePusher) -> None:
    """Release one :func:`attach_pusher` attachment; the last one
    stops the pusher (final flush included).  A pusher built directly
    (no shared key) just stops."""
    key = getattr(pusher, "_shared_key", None)
    if key is None:
        pusher.stop()
        return
    with _SHARED_LOCK:
        pusher._shared_refs -= 1
        last = pusher._shared_refs <= 0
        if last:
            _SHARED.pop(key, None)
    if last:
        pusher.stop()


if __name__ == "__main__":
    raise SystemExit(main())
