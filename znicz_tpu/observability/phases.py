"""Per-phase timing that feeds the registry and the span tracer.

:class:`PhaseTimer` is the drop-in successor of
``utils.profiling.StepTimer`` for production paths: the same
``with timer.phase(name):`` call sites, but every phase now (1) emits a
tracer span (Perfetto timeline + jax TraceAnnotation alignment) and
(2) observes into ONE shared registry histogram labeled by phase —
so ``/metrics``, ``status.json`` and the bench all read the same
ledger instead of each keeping their own totals dict.

``summary()`` stays StepTimer-shaped (``{phase: {total_s, count,
mean_ms}}``) but is computed as a DELTA against a baseline captured at
construction (or the last ``reset()``): the registry series are
process-lifetime, while a workflow/engine instance only wants to report
its own window.  Two instances sharing the metric therefore see their
own counts as long as they don't run interleaved — the registry itself
always holds the process-wide truth.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional, Sequence, Tuple

from znicz_tpu.observability.registry import (
    DEFAULT_TIME_BUCKETS,
    MetricsRegistry,
    get_registry,
)
from znicz_tpu.observability.tracing import Tracer, get_tracer


class PhaseTimer:
    """StepTimer-compatible phase ledger backed by a registry histogram."""

    def __init__(
        self,
        metric: str = "znicz_phase_seconds",
        *,
        help: str = "per-phase wall-clock seconds",
        span_prefix: str = "",
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[Tracer] = None,
        buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
    ):
        self._registry = registry if registry is not None else get_registry()
        self._hist = self._registry.histogram(
            metric, help, ("phase",), buckets=buckets
        )
        self._tracer = tracer if tracer is not None else get_tracer()
        self._prefix = span_prefix
        self._base: Dict[str, Tuple[int, float]] = {}
        self.reset()

    @contextlib.contextmanager
    def phase(self, name: str, **span_args) -> Iterator[None]:
        """Time one phase: span ``<prefix><name>`` + histogram observe.
        ``span_args`` ride into the trace event (request ids, buckets)."""
        with self._tracer.span(self._prefix + name, **span_args):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                self._hist.labels(phase=name).observe(
                    time.perf_counter() - t0
                )

    def _totals(self) -> Dict[str, Tuple[int, float]]:
        return {
            key[0]: (child.count, child.sum)
            for key, child in self._hist.children().items()
        }

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-phase ``{total_s, count, mean_ms}`` since construction or
        the last :meth:`reset` — StepTimer-shaped, registry-sourced."""
        out = {}
        for name, (count, total) in self._totals().items():
            base_count, base_total = self._base.get(name, (0, 0.0))
            if count < base_count:
                # the family was reset() behind our back (e.g. a
                # warm-up pipeline.reset_window()): the captured base
                # is stale — fall back to the fresh series as-is
                # instead of reporting empty/negative windows forever
                base_count, base_total = 0, 0.0
                self._base[name] = (0, 0.0)
            n, s = count - base_count, total - base_total
            if n > 0:
                out[name] = {
                    "total_s": s,
                    "count": n,
                    "mean_ms": 1000.0 * s / n,
                }
        return dict(
            sorted(out.items(), key=lambda kv: -kv[1]["total_s"])
        )

    def reset(self) -> None:
        """Re-baseline this instance's window (the registry keeps the
        process-lifetime series untouched)."""
        self._base = self._totals()
