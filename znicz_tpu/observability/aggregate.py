"""Fleet metrics aggregation: slaves push, the master merges.

PR 3's registry is process-wide; a fleet is many processes — a training
job plus N serving replicas — and a router (ROADMAP: prefix-aware
replica routing) schedules against the FLEET view, not any one
process's.  This module is that view, reviving the paper's master–slave
lineage (SURVEY §3.4, ``apply_data_from_slave``) as an observability
control plane in the shape Prometheus pushgateway / OpenTelemetry
collectors standardized:

* :class:`MetricsAggregator` — holds the latest registry snapshot per
  ``instance`` (each push carries its own TTL; instances that stop
  pushing expire out of the merged view), and merges live instances
  into ONE fleet-wide snapshot: counters and gauges SUM per label-set
  (right for this repo's additive-occupancy gauges — pending, inflight,
  pool blocks; age/ratio-shaped gauges do not belong in a summed fleet
  view, see docs/OBSERVABILITY.md), histograms merge BUCKET-WISE on
  the shared ladder (cumulative counts add per ``le`` edge, so the
  merged exposition keeps the histogram invariants and quantiles stay
  computable).
* :func:`build_aggregator_server` — the HTTP surface: ``POST /push``
  (JSON registry snapshot, or Prometheus text with an instance tag),
  ``GET /metrics`` / ``/metrics.json`` (the merged fleet view, same two
  formats every other surface in this repo speaks), ``GET /instances``
  (who is pushing, how stale), ``GET /healthz``.
* :class:`MetricsPusher` — the slave side: a bounded background thread
  POSTing the local registry's snapshot every ``interval_s``, every
  network call timeout-bounded, failures counted and logged but NEVER
  raised into the host process (a dead aggregator must not hurt
  serving).  Fault-injectable at ``pusher.push``
  (:mod:`znicz_tpu.utils.faults`).  Wired into
  :class:`~znicz_tpu.services.web_status.StatusWriter` and
  :class:`~znicz_tpu.services.frontdoor.ServingFrontDoor` so training
  and N serving replicas land in one scrape.

Pure stdlib, like the rest of :mod:`znicz_tpu.observability`: importing
this module must never pull in jax (the aggregator typically runs on a
host with no accelerator stack at all).
"""

from __future__ import annotations

import http.client
import http.server
import json
import logging
import os
import socket
import threading
import time
import urllib.parse
from typing import Dict, List, Optional, Tuple

from znicz_tpu.observability.registry import (
    MetricsRegistry,
    _fmt_value,
    _sample,
    get_registry,
    parse_prometheus_text,
    quantile_from_cumulative,
)
from znicz_tpu.utils import faults

logger = logging.getLogger(__name__)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# kinds the merge understands; "untyped" degrades to gauge, "summary"
# families are skipped (this repo never emits them)
_MERGEABLE = ("counter", "gauge", "histogram")

# the aggregator's OWN health families, appended fresh to every merged
# view.  A pushed snapshot that carries them (an aggregator's merged
# /metrics federated into a higher tier) would otherwise be summed in
# and then silently overwritten by the local values — drop them at
# canon time instead, so only this aggregator ever speaks these names
_SELF_FAMILIES = (
    "znicz_aggregator_instances",
    "znicz_aggregator_pushes_total",
    "znicz_aggregator_merge_conflicts",
)


def _norm_le(key) -> str:
    """Canonical bucket-edge key: ``"1.0"`` and ``"1"`` (and the float
    1.0) all merge into one edge, ``"+Inf"`` stays ``"+Inf"``."""
    return _fmt_value(float(key))


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _canon_snapshot(snapshot: dict) -> dict:
    """Registry-``snapshot()``-shaped dict -> the aggregator's canonical
    per-instance form: ``{name: {"type", "help", "series":
    {label_key: series_dict}}}`` with normalized bucket keys.  Raises
    ``ValueError`` on a malformed push — the HTTP layer answers 400, a
    broken pusher must not poison the fleet view."""
    out: dict = {}
    if not isinstance(snapshot, dict):
        raise ValueError("snapshot must be a dict of metric families")
    for name, fam in snapshot.items():
        if name in _SELF_FAMILIES:
            continue  # another aggregator's self-series: never merged
        if not isinstance(fam, dict):
            raise ValueError(f"family {name!r}: want a dict with 'series'")
        kind = fam.get("type", "gauge")
        if kind == "untyped":
            kind = "gauge"
        if kind not in _MERGEABLE:
            # summaries and self-describing side entries (bench's
            # {"type": "slo", ...} rides next to the metric families):
            # not mergeable — skip them, don't 400 the whole push
            continue
        if "series" not in fam:
            raise ValueError(f"family {name!r}: want a dict with 'series'")
        series: dict = {}
        for s in fam["series"]:
            if not isinstance(s, dict):
                raise ValueError(
                    f"family {name!r}: series entries must be objects"
                )
            labels = dict(s.get("labels") or {})
            key = _label_key(labels)
            if kind == "histogram":
                try:
                    buckets = {
                        _norm_le(le): float(c)
                        for le, c in dict(s["buckets"]).items()
                    }
                    series[key] = {
                        "labels": labels,
                        "count": float(s["count"]),
                        "sum": float(s["sum"]),
                        "buckets": buckets,
                    }
                except (KeyError, TypeError, ValueError) as exc:
                    raise ValueError(
                        f"family {name!r}: malformed histogram series: "
                        f"{exc}"
                    ) from exc
            else:
                try:
                    series[key] = {
                        "labels": labels, "value": float(s["value"])
                    }
                except (KeyError, TypeError, ValueError) as exc:
                    raise ValueError(
                        f"family {name!r}: malformed series: {exc}"
                    ) from exc
        out[name] = {
            "type": kind, "help": str(fam.get("help", "")),
            "series": series,
        }
    return out


def _canon_prom_text(text: str) -> dict:
    """Prometheus text exposition -> the same canonical form (so a
    pusher may POST either its JSON snapshot or its ``/metrics`` body).
    Histogram families are reassembled from their ``_bucket`` /
    ``_sum`` / ``_count`` samples."""
    parsed = parse_prometheus_text(text)  # raises ValueError when bad
    types, helps = parsed["types"], parsed["helps"]
    hist = {n for n, k in types.items() if k == "histogram"}
    out: dict = {}

    def fam_for(base: str) -> dict:
        kind = types.get(base, "gauge")
        if kind == "untyped":
            kind = "gauge"
        return out.setdefault(
            base,
            {"type": kind, "help": helps.get(base, ""), "series": {}},
        )

    for name, labels, value in parsed["samples"]:
        base, role = name, None
        for h in hist:
            if name == f"{h}_bucket":
                base, role = h, "bucket"
            elif name == f"{h}_sum":
                base, role = h, "sum"
            elif name == f"{h}_count":
                base, role = h, "count"
            else:
                continue
            break
        kind = types.get(base, "gauge")
        if kind not in _MERGEABLE and kind != "untyped":
            continue
        if base in _SELF_FAMILIES:
            continue  # another aggregator's self-series: never merged
        fam = fam_for(base)
        if role is not None:
            key = _label_key(
                {k: v for k, v in labels.items() if k != "le"}
            )
            ser = fam["series"].setdefault(
                key,
                {
                    "labels": {
                        k: v for k, v in labels.items() if k != "le"
                    },
                    "count": 0.0, "sum": 0.0, "buckets": {},
                },
            )
            if role == "bucket":
                ser["buckets"][_norm_le(labels["le"])] = float(value)
            else:
                ser[role] = float(value)
        else:
            fam["series"][_label_key(labels)] = {
                "labels": dict(labels), "value": float(value)
            }
    return out


def series_value(
    families: Optional[dict],
    name: str,
    labels: Optional[Dict[str, str]] = None,
) -> Optional[float]:
    """Counter/gauge series value out of a canonicalized families dict
    (as returned by :meth:`MetricsAggregator.instance_families`); None
    when the family/series is absent or is a histogram."""
    if families is None:
        return None
    fam = families.get(name)
    if fam is None or fam.get("type") == "histogram":
        return None
    ser = fam["series"].get(_label_key(dict(labels or {})))
    if ser is None:
        return None
    return float(ser["value"])


def _cumulative_pairs(buckets: Dict[str, float]) -> List[Tuple[float, float]]:
    return sorted(
        ((float(le), float(c)) for le, c in buckets.items()),
        key=lambda p: p[0],
    )


class _Instance:
    __slots__ = ("families", "pushed_at", "ttl_s", "pushes")

    def __init__(self, families: dict, ttl_s: float, now: float):
        self.families = families
        self.pushed_at = now
        self.ttl_s = ttl_s
        self.pushes = 1


class MetricsAggregator:
    """Thread-safe last-push-wins store of per-instance registry
    snapshots with a merged fleet view.

    Each push REPLACES that instance's snapshot (the registries are
    cumulative — the latest snapshot is the whole story), carries its
    own TTL (default ``default_ttl_s``), and an instance whose TTL
    lapses silently leaves the merged view — a crashed replica stops
    counting without ever unwinding anything."""

    def __init__(self, *, default_ttl_s: float = 60.0):
        if default_ttl_s <= 0:
            raise ValueError(
                f"want default_ttl_s > 0; got {default_ttl_s}"
            )
        self.default_ttl_s = float(default_ttl_s)
        self._lock = threading.Lock()
        self._instances: Dict[str, _Instance] = {}
        self._n_pushes = 0

    # -- intake ------------------------------------------------------------

    def push(
        self,
        instance: str,
        snapshot: Optional[dict] = None,
        *,
        text: Optional[str] = None,
        ttl_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> None:
        """Record one push for ``instance`` — exactly one of
        ``snapshot`` (registry ``snapshot()`` dict) or ``text``
        (Prometheus exposition).  Raises ``ValueError`` on malformed
        input; never partially applies."""
        if not instance:
            raise ValueError("push needs a non-empty instance name")
        if (snapshot is None) == (text is None):
            raise ValueError("push wants exactly one of snapshot= or text=")
        families = (
            _canon_snapshot(snapshot)
            if snapshot is not None
            else _canon_prom_text(text)
        )
        ttl = float(ttl_s) if ttl_s is not None else self.default_ttl_s
        if ttl <= 0:
            raise ValueError(f"want ttl_s > 0; got {ttl}")
        t = time.monotonic() if now is None else now
        with self._lock:
            prev = self._instances.get(str(instance))
            inst = _Instance(families, ttl, t)
            if prev is not None:
                inst.pushes = prev.pushes + 1
            self._instances[str(instance)] = inst
            self._n_pushes += 1

    def forget(self, instance: str) -> bool:
        """Drop ``instance`` immediately (an orderly replica shutdown
        need not wait for its TTL)."""
        with self._lock:
            return self._instances.pop(str(instance), None) is not None

    # -- views -------------------------------------------------------------

    def _live(self, now: Optional[float]) -> Dict[str, _Instance]:
        t = time.monotonic() if now is None else now
        with self._lock:
            stale = [
                name for name, inst in self._instances.items()
                if t - inst.pushed_at > inst.ttl_s
            ]
            for name in stale:
                del self._instances[name]
            return dict(self._instances)

    def instances(self, now: Optional[float] = None) -> List[dict]:
        """Live pushers: name, seconds since last push, TTL, push count."""
        t = time.monotonic() if now is None else now
        return [
            {
                "instance": name,
                "age_s": round(t - inst.pushed_at, 3),
                "ttl_s": inst.ttl_s,
                "pushes": inst.pushes,
            }
            for name, inst in sorted(self._live(now).items())
        ]

    def instance_families(
        self, instance: str, now: Optional[float] = None
    ) -> Optional[dict]:
        """ONE live instance's canonicalized families dict (None when
        unknown/stale).  Treat as read-only; extract series with
        :func:`series_value`.  One staleness sweep + lock acquisition
        buys every per-instance read a caller needs — the cluster
        router reads five load series per replica per routing
        decision through this."""
        inst = self._live(now).get(str(instance))
        return None if inst is None else inst.families

    def instance_value(
        self,
        instance: str,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """ONE instance's latest counter/gauge series value, or None
        when the instance is unknown/stale, the family absent, or the
        series is a histogram.  The merged view sums across instances,
        which is exactly the wrong shape for picking between them."""
        return series_value(
            self.instance_families(instance, now), name, labels
        )

    def merged_snapshot(self, now: Optional[float] = None) -> dict:
        """The fleet view in registry-``snapshot()`` shape: counters and
        gauges summed per label-set across live instances, histograms
        merged bucket-wise (same ladder; a series whose ladder disagrees
        with the already-merged one is SKIPPED and tallied as a
        conflict, never silently mis-summed), with p50/p95/p99
        re-interpolated from the merged cumulative buckets."""
        live = self._live(now)
        merged: dict = {}
        conflicts = 0
        for inst_name in sorted(live):
            for name, fam in live[inst_name].families.items():
                m = merged.get(name)
                if m is None:
                    m = merged[name] = {
                        "type": fam["type"], "help": fam["help"],
                        "series": {},
                    }
                elif m["type"] != fam["type"]:
                    conflicts += 1
                    logger.warning(
                        "aggregator: %s is %s on %s but %s in the "
                        "merged view; skipping that instance's family",
                        name, fam["type"], inst_name, m["type"],
                    )
                    continue
                for key, ser in fam["series"].items():
                    cur = m["series"].get(key)
                    if m["type"] == "histogram":
                        if cur is None:
                            m["series"][key] = {
                                "labels": dict(ser["labels"]),
                                "count": ser["count"],
                                "sum": ser["sum"],
                                "buckets": dict(ser["buckets"]),
                            }
                        elif set(cur["buckets"]) != set(ser["buckets"]):
                            conflicts += 1
                            logger.warning(
                                "aggregator: %s bucket ladder from %s "
                                "does not match the merged ladder; "
                                "skipping that series", name, inst_name,
                            )
                        else:
                            cur["count"] += ser["count"]
                            cur["sum"] += ser["sum"]
                            for le in cur["buckets"]:
                                cur["buckets"][le] += ser["buckets"][le]
                    else:
                        if cur is None:
                            m["series"][key] = {
                                "labels": dict(ser["labels"]),
                                "value": ser["value"],
                            }
                        else:
                            cur["value"] += ser["value"]
        with self._lock:
            n_pushes = self._n_pushes
        out: dict = {}
        for name in sorted(merged):
            fam = merged[name]
            series = []
            for key in sorted(fam["series"]):
                ser = fam["series"][key]
                if fam["type"] == "histogram":
                    cum = _cumulative_pairs(ser["buckets"])
                    series.append(
                        {
                            "labels": ser["labels"],
                            "count": ser["count"],
                            "sum": ser["sum"],
                            "buckets": {
                                _fmt_value(u): c for u, c in cum
                            },
                            "p50": quantile_from_cumulative(cum, 0.5),
                            "p95": quantile_from_cumulative(cum, 0.95),
                            "p99": quantile_from_cumulative(cum, 0.99),
                        }
                    )
                else:
                    series.append(
                        {"labels": ser["labels"], "value": ser["value"]}
                    )
            out[name] = {
                "type": fam["type"], "help": fam["help"],
                "series": series,
            }
        # the aggregator's own health, visible in the same scrape
        out["znicz_aggregator_instances"] = {
            "type": "gauge",
            "help": "live (unexpired) instances in the fleet view",
            "series": [{"labels": {}, "value": float(len(live))}],
        }
        out["znicz_aggregator_pushes_total"] = {
            "type": "counter",
            "help": "snapshot pushes accepted since aggregator start",
            "series": [{"labels": {}, "value": float(n_pushes)}],
        }
        # a GAUGE of the current view, not a counter: the conflict set
        # is recomputed per merge from the live instances, and reads
        # must not mutate state (a counter here would scale with
        # scrape frequency, not with pushes)
        out["znicz_aggregator_merge_conflicts"] = {
            "type": "gauge",
            "help": (
                "series skipped in this merged view "
                "(kind or ladder mismatch)"
            ),
            "series": [{"labels": {}, "value": float(conflicts)}],
        }
        return out

    def prometheus_text(self, now: Optional[float] = None) -> str:
        """The merged fleet view as a parse-clean text exposition
        (format 0.0.4) — what a real Prometheus scrapes off this
        service, and what :func:`parse_prometheus_text` round-trips in
        the tier-1 acceptance test."""
        lines: List[str] = []
        for name, fam in self.merged_snapshot(now).items():
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['type']}")
            for ser in fam["series"]:
                base = sorted(ser["labels"].items())
                if fam["type"] == "histogram":
                    for upper, acc in _cumulative_pairs(ser["buckets"]):
                        lines.append(
                            _sample(
                                f"{name}_bucket",
                                base + [("le", _fmt_value(upper))],
                                acc,
                            )
                        )
                    lines.append(_sample(f"{name}_sum", base, ser["sum"]))
                    lines.append(
                        _sample(f"{name}_count", base, ser["count"])
                    )
                else:
                    lines.append(_sample(name, base, ser["value"]))
        return "\n".join(lines) + "\n"


# -- the HTTP surface -------------------------------------------------------


class AggregatorRequestHandler(http.server.BaseHTTPRequestHandler):
    """``POST /push`` + the merged read endpoints.  Every response
    carries an explicit Content-Length (no streaming here)."""

    protocol_version = "HTTP/1.1"
    aggregator: MetricsAggregator  # set by build_aggregator_server

    def log_message(self, fmt, *args):  # noqa: A003 — http.server API
        logger.debug("aggregator http: " + fmt, *args)

    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            self._send(
                self.aggregator.prometheus_text().encode(),
                PROM_CONTENT_TYPE,
            )
        elif path == "/metrics.json":
            self._send_json(self.aggregator.merged_snapshot())
        elif path == "/instances":
            inst = self.aggregator.instances()
            self._send_json({"instances": inst, "live": len(inst)})
        elif path == "/healthz":
            self._send(b"ok\n", "text/plain")
        else:
            self._send_json({"error": "unknown endpoint"}, status=404)

    def do_POST(self):  # noqa: N802 — http.server API
        path, _, query = self.path.partition("?")
        if path != "/push":
            self._send_json({"error": "unknown endpoint"}, status=404)
            return
        qs = urllib.parse.parse_qs(query)
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(n)
            ctype = (self.headers.get("Content-Type") or "").split(";")[0]
            ttl_raw = (
                qs.get("ttl_s", [None])[0]
                or self.headers.get("X-Znicz-Ttl")
            )
            ttl_s = float(ttl_raw) if ttl_raw is not None else None
            if ctype == "application/json":
                payload = json.loads(body or b"{}")
                if not isinstance(payload, dict):
                    raise ValueError("JSON push body must be an object")
                instance = payload.get("instance")
                if not instance:
                    raise ValueError("JSON push needs an 'instance' key")
                if payload.get("ttl_s") is not None:
                    ttl_s = float(payload["ttl_s"])
                self.aggregator.push(
                    instance, payload.get("snapshot"), ttl_s=ttl_s
                )
            else:  # Prometheus text: instance rides query/header
                instance = (
                    qs.get("instance", [None])[0]
                    or self.headers.get("X-Znicz-Instance")
                )
                if not instance:
                    raise ValueError(
                        "text push needs ?instance= or X-Znicz-Instance"
                    )
                self.aggregator.push(
                    instance, text=body.decode("utf-8"), ttl_s=ttl_s
                )
        except (ValueError, TypeError, UnicodeDecodeError) as exc:
            self._send_json(
                {"error": "bad_push", "detail": str(exc)}, status=400
            )
            return
        self._send_json(
            {"ok": True, "live": len(self.aggregator.instances())}
        )

    def _send_json(self, obj: dict, status: int = 200) -> None:
        self._send(
            (json.dumps(obj) + "\n").encode(), "application/json",
            status=status,
        )

    def _send(self, body: bytes, content_type: str, status: int = 200):
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def build_aggregator_server(
    aggregator: Optional[MetricsAggregator] = None,
    port: int = 9109,
    host: str = "127.0.0.1",
) -> http.server.ThreadingHTTPServer:
    """A ready-to-serve aggregator; ``port=0`` binds ephemeral (read it
    back from ``server.server_address``).  The aggregator instance is
    reachable as ``server.aggregator``."""
    agg = aggregator if aggregator is not None else MetricsAggregator()
    handler = type(
        "BoundAggregatorHandler",
        (AggregatorRequestHandler,),
        {"aggregator": agg},
    )
    server = http.server.ThreadingHTTPServer((host, port), handler)
    server.aggregator = agg
    return server


def main(argv=None) -> int:
    """``python -m znicz_tpu.observability.aggregate [port] [host]`` —
    run a standalone fleet aggregator (loopback by default)."""
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    port = int(args[0]) if args else 9109
    host = args[1] if len(args) > 1 else "127.0.0.1"
    server = build_aggregator_server(port=port, host=host)
    host, port = server.server_address[:2]
    print(
        f"znicz metrics aggregator on http://{host}:{port} "
        "(push to /push, scrape /metrics, roster at /instances)"
    )
    server.serve_forever()
    return 0


# -- the slave side ---------------------------------------------------------


class MetricsPusher:
    """Background registry pusher: POST the local registry snapshot to
    an aggregator every ``interval_s``, each attempt bounded by
    ``timeout_s`` and advertised with ``ttl_s = ttl_factor *
    interval_s`` (miss a few pushes and the fleet view forgets you).

    Failures never propagate: a dead aggregator costs one log line and
    a counter tick, not a serving thread.  ``push_now()`` is the
    synchronous hook (StatusWriter calls it per epoch so the view is
    epoch-fresh; tests drive it directly).  The ``pusher.push`` fault
    point makes the failure path deterministic in CI."""

    def __init__(
        self,
        url: str,
        *,
        instance: Optional[str] = None,
        interval_s: float = 15.0,
        registry: Optional[MetricsRegistry] = None,
        timeout_s: float = 5.0,
        ttl_factor: float = 3.0,
    ):
        if interval_s <= 0:
            raise ValueError(f"want interval_s > 0; got {interval_s}")
        parsed = urllib.parse.urlsplit(url)
        if parsed.scheme != "http" or not parsed.hostname:
            raise ValueError(
                f"want an http://host:port aggregator url; got {url!r}"
            )
        self.host = parsed.hostname
        self.port = parsed.port or 80
        base = parsed.path.rstrip("/")
        self.path = base + "/push" if not base.endswith("/push") else base
        self.instance = (
            instance
            if instance
            else f"{socket.gethostname()}-{os.getpid()}"
        )
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.ttl_s = float(ttl_factor) * self.interval_s
        self._registry = registry if registry is not None else get_registry()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.pushes_ok = 0
        self.pushes_failed = 0
        self._m_pushes = self._registry.counter(
            "znicz_pusher_pushes_total",
            "aggregator pushes attempted by this process, by outcome",
            ("status",),
        )
        # push lag: seconds since the last SUCCESSFUL push, stamped at
        # each attempt BEFORE the snapshot is taken — so the pushed
        # snapshot itself carries how stale the previous one was, and a
        # silently wedged/failing pusher is visible from the fleet view
        # the moment any push lands again (a fully dead pusher shows as
        # /instances age_s instead)
        self._m_lag = self._registry.gauge(
            "znicz_pusher_lag_seconds",
            "seconds since this process's last successful aggregator "
            "push, as of its most recent attempt",
        )
        self._last_ok: Optional[float] = None

    def start(self) -> "MetricsPusher":
        """Start the background push loop (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        # _loop only calls push_now, whose contract is "never raises"
        # (every failure is caught, counted and logged inside it)
        self._thread = threading.Thread(  # znicz-check: disable=ZNC013
            target=self._loop,
            name=f"znicz-pusher-{self.instance}",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Stop the loop; the thread makes one final flush push (so the
        last snapshot before shutdown lands) before exiting.  Bounded:
        the flush itself is timeout-bounded, and the join waits at most
        ``timeout`` (default: push timeout + 2 intervals)."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(
                timeout=(
                    timeout
                    if timeout is not None
                    else self.timeout_s + 2 * self.interval_s
                )
            )

    def __enter__(self) -> "MetricsPusher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            self.push_now()
        self.push_now()  # final flush: the shutdown-instant snapshot

    def push_now(self) -> bool:
        """One synchronous, bounded push; True on 2xx.  Never raises."""
        now = time.monotonic()
        self._m_lag.set(
            round(now - self._last_ok, 3)
            if self._last_ok is not None
            else 0.0
        )
        try:
            faults.fire("pusher.push")
            body = json.dumps(
                {
                    "instance": self.instance,
                    "ttl_s": self.ttl_s,
                    "snapshot": self._registry.snapshot(),
                }
            ).encode()
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s
            )
            try:
                conn.request(
                    "POST", self.path, body=body,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                resp.read()
                ok = 200 <= resp.status < 300
            finally:
                conn.close()
        except Exception as exc:
            self.pushes_failed += 1
            self._m_pushes.labels(status="error").inc()
            logger.debug(
                "metrics push to %s:%s failed: %s",
                self.host, self.port, exc,
            )
            return False
        if ok:
            self.pushes_ok += 1
            self._last_ok = time.monotonic()
            self._m_pushes.labels(status="ok").inc()
        else:
            self.pushes_failed += 1
            self._m_pushes.labels(status="error").inc()
            logger.debug(
                "metrics push to %s:%s rejected: HTTP %s",
                self.host, self.port, resp.status,
            )
        return ok


if __name__ == "__main__":
    raise SystemExit(main())
