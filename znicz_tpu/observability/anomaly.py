"""Step anomaly flight recorder: typed verdicts over the training loop.

The serving tier fails loudly (typed errors, watchdog restarts); the
training tier until now failed silently — a NaN loss just trains
garbage for the remaining epochs, a 10x step-time regression is
invisible until someone reads the bench.  This module watches the
per-step watch vector the workflow's jitted train step already emits
(loss + grad norm, piggybacked on the existing compiled program — ZERO
new XLA programs) plus the consumer-side step wall, and records:

* **non-finite loss / grad norm** — ``math.isfinite`` on the lagged
  host copy of the watch vector (the copy is started asynchronously at
  dispatch and read a few steps later, so detection never adds a sync
  to the hot loop);
* **loss spikes and step-time regressions** — one-sided rolling robust
  z-scores (median + MAD over a bounded window), so a heavy-tailed but
  healthy loss curve doesn't page anyone while a genuine 8-sigma jump
  does.

Each anomaly becomes one bounded **ring entry** carrying a typed
verdict and a snapshot of the last K steps' metrics — the flight
recorder readout that survives to ``status.json`` (via
``StatusWriter``), while ``/metrics`` and the aggregator plane carry
the counters/gauges (``znicz_train_anomalies_total{type}``,
``znicz_train_anomaly_active``) that ``znicz-doctor`` gates on.

Pure stdlib — no jax, no numpy: the detector consumes plain floats.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from znicz_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)

NON_FINITE_LOSS = "non_finite_loss"
NON_FINITE_GRAD = "non_finite_grad_norm"
LOSS_SPIKE = "loss_spike"
STEP_TIME_REGRESSION = "step_time_regression"

ANOMALY_TYPES = (
    NON_FINITE_LOSS,
    NON_FINITE_GRAD,
    LOSS_SPIKE,
    STEP_TIME_REGRESSION,
)

# consistency scale factor: MAD * 1.4826 estimates sigma for a normal
_MAD_SIGMA = 1.4826


def _robust_z(value: float, history: List[float], min_scale: float) -> float:
    """One-sided robust z of ``value`` against ``history`` (median/MAD)."""
    n = len(history)
    srt = sorted(history)
    mid = n // 2
    median = srt[mid] if n % 2 else 0.5 * (srt[mid - 1] + srt[mid])
    devs = sorted(abs(v - median) for v in history)
    mad = devs[mid] if n % 2 else 0.5 * (devs[mid - 1] + devs[mid])
    scale = max(_MAD_SIGMA * mad, min_scale, 1e-12)
    return (value - median) / scale


class StepAnomalyDetector:
    """Rolling per-step anomaly watch + bounded flight-recorder ring.

    Feed it once per training step via :meth:`observe_step`; read the
    JSON-able :meth:`report` (ring + counts + active flag) from status
    surfaces.  Thread-safe: the workflow feeds it from the training
    thread while status/HTTP readers snapshot it.
    """

    def __init__(
        self,
        *,
        window: int = 64,
        z_threshold: float = 8.0,
        min_history: int = 12,
        snapshot_last: int = 8,
        ring_size: int = 16,
        active_window: int = 32,
        # floor on the robust scale as a fraction of the median: with
        # the default z_threshold=8 a verdict needs a value > ~3x the
        # rolling median, not just an 8-MAD wobble — host-timer jitter
        # is heavy-tailed and a flat loss curve has near-zero MAD
        min_scale_frac: float = 0.25,
        # a step-time REGRESSION is sustained by definition: one slow
        # step is an OS/GC blip (measured firing z=9 on sub-ms CPU
        # steps), so the verdict needs this many consecutive
        # over-threshold steps.  Loss spikes stay single-step — they
        # are deterministic values, not wall-clock jitter
        time_consecutive: int = 3,
        registry: Optional[MetricsRegistry] = None,
    ):
        if window < 2 or min_history < 2:
            raise ValueError("want window >= 2 and min_history >= 2")
        self.window = int(window)
        self.z_threshold = float(z_threshold)
        self.min_history = int(min_history)
        self.snapshot_last = int(snapshot_last)
        self.active_window = int(active_window)
        self.min_scale_frac = float(min_scale_frac)
        self.time_consecutive = max(int(time_consecutive), 1)
        self._time_over = 0
        reg = registry if registry is not None else get_registry()
        self._m_total = reg.counter(
            "znicz_train_anomalies_total",
            "training anomalies by typed verdict",
            ("type",),
        )
        self._m_active = reg.gauge(
            "znicz_train_anomaly_active",
            "1 while an anomaly fired within the last active_window "
            "steps (znicz-doctor's exit-1 gate)",
        )
        self._m_loss = reg.gauge(
            "znicz_train_last_loss", "last observed per-step train loss"
        )
        self._m_grad = reg.gauge(
            "znicz_train_last_grad_norm",
            "last observed per-step gradient (or update) global norm",
        )
        self._lock = threading.Lock()
        self._loss_hist: Deque[float] = deque(maxlen=self.window)
        self._time_hist: Deque[float] = deque(maxlen=self.window)
        self._recent: Deque[dict] = deque(maxlen=max(snapshot_last, 1))
        self._ring: Deque[dict] = deque(maxlen=max(ring_size, 1))
        self._counts: Dict[str, int] = {}
        self._last_anomaly_step: Optional[int] = None
        self._last_step: Optional[int] = None

    # -- feeding -----------------------------------------------------------

    def observe_step(
        self,
        step: int,
        *,
        loss: float,
        grad_norm: Optional[float] = None,
        step_seconds: Optional[float] = None,
    ) -> List[dict]:
        """Record one step; returns the anomalies it raised (possibly
        empty).  ``grad_norm``/``step_seconds`` are optional — the
        scanned epoch path has no per-step wall, a workflow without the
        watch piggyback has no grad norm."""
        loss = float(loss)
        anomalies: List[dict] = []
        with self._lock:
            self._last_step = int(step)
            if not math.isfinite(loss):
                anomalies.append(
                    self._raise(NON_FINITE_LOSS, step, loss, None)
                )
            elif len(self._loss_hist) >= self.min_history:
                z = _robust_z(
                    loss,
                    list(self._loss_hist),
                    self.min_scale_frac
                    * abs(self._median(self._loss_hist)),
                )
                if z >= self.z_threshold:
                    anomalies.append(
                        self._raise(LOSS_SPIKE, step, loss, z)
                    )
            if grad_norm is not None and not math.isfinite(
                float(grad_norm)
            ):
                anomalies.append(
                    self._raise(
                        NON_FINITE_GRAD, step, float(grad_norm), None
                    )
                )
            if step_seconds is not None:
                t = float(step_seconds)
                if (
                    math.isfinite(t)
                    and len(self._time_hist) >= self.min_history
                ):
                    z = _robust_z(
                        t,
                        list(self._time_hist),
                        self.min_scale_frac
                        * abs(self._median(self._time_hist)),
                    )
                    if z >= self.z_threshold:
                        self._time_over += 1
                        if self._time_over >= self.time_consecutive:
                            anomalies.append(
                                self._raise(
                                    STEP_TIME_REGRESSION, step, t, z
                                )
                            )
                            self._time_over = 0
                    else:
                        self._time_over = 0
                if math.isfinite(t):
                    self._time_hist.append(t)
            # only finite values enter the baselines: a NaN-poisoned
            # window would make every later median NaN and mute the
            # detector exactly when it matters
            if math.isfinite(loss):
                self._loss_hist.append(loss)
            self._recent.append(
                {
                    "step": int(step),
                    "loss": loss if math.isfinite(loss) else None,
                    "grad_norm": (
                        float(grad_norm)
                        if grad_norm is not None
                        and math.isfinite(float(grad_norm))
                        else None
                    ),
                    "step_seconds": (
                        round(float(step_seconds), 6)
                        if step_seconds is not None
                        else None
                    ),
                }
            )
            active = self._active_locked()
        self._m_active.set(1.0 if active else 0.0)
        if math.isfinite(loss):
            self._m_loss.set(loss)
        if grad_norm is not None and math.isfinite(float(grad_norm)):
            self._m_grad.set(float(grad_norm))
        for a in anomalies:
            self._m_total.labels(type=a["type"]).inc()
        return anomalies

    @staticmethod
    def _median(values) -> float:
        srt = sorted(values)
        n = len(srt)
        if not n:
            return 0.0
        mid = n // 2
        return srt[mid] if n % 2 else 0.5 * (srt[mid - 1] + srt[mid])

    def _raise(
        self,
        kind: str,
        step: int,
        value: float,
        z: Optional[float],
    ) -> dict:
        entry = {
            "type": kind,
            "step": int(step),
            "value": value if math.isfinite(value) else repr(value),
            "zscore": round(z, 2) if z is not None else None,
            "z_threshold": self.z_threshold,
            "unix": time.time(),  # timestamp, not a duration
            # the flight-recorder readout: the last K steps leading in
            "snapshot": list(self._recent),
        }
        self._ring.append(entry)
        self._counts[kind] = self._counts.get(kind, 0) + 1
        self._last_anomaly_step = int(step)
        return entry

    def _active_locked(self) -> bool:
        return (
            self._last_anomaly_step is not None
            and self._last_step is not None
            and self._last_step - self._last_anomaly_step
            <= self.active_window
        )

    # -- reading -----------------------------------------------------------

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active_locked()

    def report(self) -> dict:
        """JSON-able flight-recorder readout (embedded in
        ``status.json`` next to the metrics snapshot)."""
        with self._lock:
            return {
                "active": self._active_locked(),
                "counts": dict(sorted(self._counts.items())),
                "total": sum(self._counts.values()),
                "last_anomaly_step": self._last_anomaly_step,
                "last_step": self._last_step,
                "ring": [dict(e) for e in self._ring],
            }
