"""Launcher / CLI.

Capability parity with ``veles/__main__.py`` + ``veles/launcher.py``
[SURVEY.md 2.1 "Launcher / CLI", 3.1]: ``python -m znicz_tpu <workflow.py>
[config.py] --flags`` loads the workflow module, applies the config module's
``root`` overrides, then drives the module's ``run(load, main)`` convention —
the same two-file UX the reference samples use.

Flag mapping from the reference (SURVEY.md 5.6):
  --device        device selection (tpu / cpu; reference: OpenCL/CUDA ordinal)
  --random-seed   seeds the named PRNG registry
  --snapshot      resume from a snapshot file
  --snapshot-dir  where snapshots are written
  --data-parallel shard the batch over all local devices (replaces
                  --listen/--master-address: no master process exists,
                  SURVEY.md 3.4)
  --optimize      genetic hyperparameter search (veles --optimize)

Self-healing additions (docs/TRAINING.md "Self-healing training"):
``--resume auto`` resumes from the newest VALID snapshot in
``--snapshot-dir`` (corrupt files skipped) or starts fresh;
``--supervise`` runs the training command as a supervised child process
and restarts it on crash with exponential backoff under a
``--max-restarts`` budget, each restart resuming via ``--resume auto``;
SIGTERM/SIGINT drain the in-flight step, write an emergency snapshot
and exit with the documented code ``EXIT_PREEMPTED`` (75).

Exit codes: 0 done; 75 gracefully preempted (emergency snapshot
written — resume me); anything else: crash (the supervisor restarts
while its budget lasts, then exits with the child's last code).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

from znicz_tpu.core.config import root
from znicz_tpu.core.logger import Logger, setup_logging

# re-exported convenience: the documented graceful-preemption exit code
from znicz_tpu.workflow.recovery import EXIT_PREEMPTED  # noqa: F401

# supervisor-only flags, stripped from the child's argv (flag -> has value)
_SUPERVISOR_FLAGS = {
    "--supervise": False,
    "--max-restarts": True,
    "--restart-backoff": True,
}


def _load_module(path: str, name: str):
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load module from {path}")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m znicz_tpu",
        description="TPU-native VELES/Znicz: run a workflow module",
        # no prefix abbreviation: the supervisor strips its own flags
        # from the child argv by EXACT spelling — an abbreviated
        # --super reaching the child would recurse into a nested
        # supervisor chain
        allow_abbrev=False,
    )
    p.add_argument("workflow", help="path to the workflow module (.py)")
    p.add_argument(
        "config", nargs="?", default=None,
        help="optional config module mutating znicz_tpu.root",
    )
    p.add_argument("--device", default=None, choices=["tpu", "cpu"],
                   help="force a jax platform (default: jax's choice)")
    p.add_argument("--random-seed", type=int, default=None)
    p.add_argument("--snapshot", default=None,
                   help="resume training from this snapshot file")
    p.add_argument("--resume", default=None, choices=["auto"],
                   metavar="MODE",
                   help="'auto': resume from the newest VALID snapshot "
                        "in --snapshot-dir (corrupt/truncated files are "
                        "skipped), or start fresh when none exists; "
                        "overrides --snapshot")
    p.add_argument("--supervise", action="store_true",
                   help="run training as a supervised child process: "
                        "restart it on crash with exponential backoff "
                        "(resuming via --resume auto), forward "
                        "SIGTERM/SIGINT, record restart history in "
                        "supervisor.json")
    p.add_argument("--max-restarts", type=int, default=3, metavar="N",
                   help="supervisor restart budget (default 3); past it "
                        "the supervisor exits with the child's last code")
    p.add_argument("--restart-backoff", type=float, default=1.0,
                   metavar="SECONDS",
                   help="initial restart backoff, doubled per restart "
                        "and capped at 60s (default 1.0; 0 disables)")
    p.add_argument("--snapshot-interval", type=int, default=None,
                   metavar="K",
                   help="also snapshot every K epochs (composes with "
                        "best-model snapshots in both epoch-sync modes)")
    p.add_argument("--snapshot-dir", default=None,
                   help="write snapshots under this directory")
    p.add_argument("--data-parallel", action="store_true",
                   help="shard batches over all local devices")
    p.add_argument("--mesh", default=None, metavar="SPEC",
                   help="device mesh spec, e.g. data=4,model=2 — shards "
                        "batches over 'data' and weights over 'model' "
                        "(tensor parallel); implies --data-parallel")
    # multi-host bring-up (replaces the reference's --listen /
    # --master-address master-slave pair, SURVEY.md 3.4): every host runs
    # the SAME command with its own --process-id; the coordinator address
    # is the rendezvous, not a data channel.
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="multi-host rendezvous address (jax.distributed); "
                        "on TPU pod slices omit all three flags — topology "
                        "autodetects")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--data-dir", default=None,
                   help="dataset directory for the workflow's loader "
                        "(sets root.common.data_dir; model modules fall "
                        "back to it when their loader config has none)")
    p.add_argument("--stop-after", type=int, default=None, metavar="EPOCHS",
                   help="override the workflow's max_epochs")
    p.add_argument("--optimize", type=int, default=None, metavar="GENS",
                   help="genetic hyperparameter search for N generations")
    p.add_argument("--optimize-workers", type=int, default=0, metavar="N",
                   help="evaluate each generation in N spawned worker "
                        "processes (reference: concurrent workflow "
                        "instances); deterministic given --random-seed and "
                        "independent of N. Combine with --device cpu on a "
                        "single shared accelerator")
    p.add_argument("--export", default=None, metavar="MODEL.znicz",
                   help="after training, export the model for the native "
                        "inference engine (native/znicz_infer)")
    p.add_argument("--evaluate", nargs="?", const="test", default=None,
                   metavar="SPLIT",
                   help="evaluation-only mode (reference test runs): build "
                        "the workflow, restore --snapshot if given, run one "
                        "evaluation pass over SPLIT (default: test) with the "
                        "confusion matrix, print a JSON summary and exit "
                        "without training")
    p.add_argument("--epoch-sync", default=None,
                   choices=["sync", "deferred"],
                   help="deferred: overlap the per-epoch metric fetch with "
                        "the next epoch's dispatch (verdicts lag one epoch; "
                        "stop decisions stay exact; best-model snapshots "
                        "write from a retained one-epoch buffer)")
    p.add_argument("--dry-run", action="store_true",
                   help="build and initialize the workflow, run nothing")
    p.add_argument("--verbose", action="store_true")
    return p


class Launcher(Logger):
    """Owns CLI args; hands the workflow module its ``load``/``main`` pair."""

    def __init__(self, args: argparse.Namespace):
        self.args = args
        self.workflow = None
        self.result = None

    # -- the module-facing convention (reference run(load, main)) ---------
    def load(self, workflow_cls, *wf_args, **wf_kwargs):
        """Construct the workflow, applying CLI overrides."""
        if self.args.snapshot_dir and "snapshot_dir" not in wf_kwargs:
            wf_kwargs["snapshot_dir"] = self.args.snapshot_dir
        if getattr(self.args, "snapshot_interval", None):
            sc = dict(wf_kwargs.get("snapshot_config") or {})
            sc.setdefault("interval", self.args.snapshot_interval)
            wf_kwargs["snapshot_config"] = sc
        if (
            getattr(self.args, "epoch_sync", None)
            and "epoch_sync" not in wf_kwargs
        ):
            wf_kwargs["epoch_sync"] = self.args.epoch_sync
        if self.args.stop_after is not None:
            dc = dict(wf_kwargs.get("decision_config") or {})
            dc["max_epochs"] = self.args.stop_after
            wf_kwargs["decision_config"] = dc
        if (
            self.args.data_parallel or getattr(self.args, "mesh", None)
        ) and "parallel" not in wf_kwargs:
            import inspect

            from znicz_tpu.parallel import (
                MODEL_AXIS,
                DataParallel,
                mesh_from_spec,
            )

            if getattr(self.args, "mesh", None):
                mesh = mesh_from_spec(self.args.mesh)
                dp = DataParallel(mesh, tp=mesh.shape.get(MODEL_AXIS, 1) > 1)
            else:
                dp = DataParallel()
            # Signature check (not try/except TypeError): an unrelated
            # TypeError raised inside the constructor must propagate, not
            # silently retry without DP.
            try:
                sig = inspect.signature(workflow_cls)
                accepts = "parallel" in sig.parameters or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in sig.parameters.values()
                )
                # A module may already pass `parallel` positionally; its
                # explicit choice wins over the CLI default — injecting the
                # kwarg would raise "multiple values for 'parallel'".
                try:
                    bound = sig.bind(*wf_args, **wf_kwargs)
                    if "parallel" in bound.arguments:
                        self.workflow = workflow_cls(*wf_args, **wf_kwargs)
                        return self.workflow
                # bind failure: let the real constructor report it
                except TypeError:  # znicz-check: disable=ZNC008
                    pass
            except (TypeError, ValueError):  # C callables, odd metaclasses
                accepts = True
            if accepts:
                self.workflow = workflow_cls(
                    *wf_args, **{**wf_kwargs, "parallel": dp}
                )
            else:
                # user workflows predating the kwarg: attribute assignment
                # before initialize() has identical semantics
                self.workflow = workflow_cls(*wf_args, **wf_kwargs)
                self.workflow.parallel = dp
            return self.workflow
        self.workflow = workflow_cls(*wf_args, **wf_kwargs)
        return self.workflow

    def _resolve_auto_resume(self, exclude=()):
        """``--resume auto`` -> the newest valid snapshot path (or None
        for a fresh start).  Resolved HERE, once the workflow exists,
        so the search is scoped to the workflow's own snapshot prefix —
        a shared directory must never hand back another model's
        checkpoint (a shape-mismatch crash loop under --supervise)."""
        from znicz_tpu.workflow.snapshotter import find_latest_valid

        snapshotter = getattr(self.workflow, "snapshotter", None)
        directory = self.args.snapshot_dir or getattr(
            snapshotter, "directory", None
        )
        if not directory:
            raise SystemExit(
                "--resume auto needs --snapshot-dir (or a workflow "
                "snapshotter) to know where to look"
            )
        found = find_latest_valid(
            directory,
            prefix=getattr(snapshotter, "prefix", None),
            exclude=exclude,
        )
        if found:
            self.info("--resume auto: resuming from %s", found)
        else:
            self.info(
                "--resume auto: no valid snapshot under %s; starting "
                "fresh", directory,
            )
        return found

    def _initialize_with_auto_resume(self, **kwargs) -> None:
        """Initialize, quarantining auto-resolved snapshots that pass
        verification (a digest check) but still fail to LOAD — e.g. a
        pickle referencing a since-renamed class.  Falling through to
        the next older snapshot keeps ``--supervise`` from burning its
        whole restart budget on one bad file."""
        from znicz_tpu.workflow.snapshotter import SnapshotCorruptError

        tried: set = set()
        while True:
            self.args.snapshot = self._resolve_auto_resume(exclude=tried)
            try:
                self.workflow.initialize(
                    seed=self.args.random_seed,
                    snapshot=self.args.snapshot,
                    **kwargs,
                )
                return
            except (SnapshotCorruptError, ValueError):
                if not self.args.snapshot:
                    raise  # a fresh start failed: not a snapshot issue
                self.logger.exception(
                    "--resume auto: %s failed to load; trying an "
                    "older snapshot", self.args.snapshot,
                )
                tried.add(self.args.snapshot)

    def main(self, **kwargs):
        """Initialize and run the loaded workflow."""
        if self.workflow is None:
            raise RuntimeError("run(load, main): call load(...) before main()")
        if self.args.export:
            # fail BEFORE training, not after hours of it: class AND layer
            # types must be native-engine compatible
            from znicz_tpu.export import validate_exportable

            if not hasattr(self.workflow.model, "_replace"):
                raise SystemExit(
                    "--export supports layer-list models (StandardWorkflow); "
                    f"{type(self.workflow).__name__} has no exportable model"
                )
            try:
                validate_exportable(self.workflow.model)
            except ValueError as e:
                raise SystemExit(f"--export: {e}") from None
        if self.args.resume == "auto":
            self._initialize_with_auto_resume(**kwargs)
        else:
            self.workflow.initialize(
                seed=self.args.random_seed, snapshot=self.args.snapshot,
                **kwargs,
            )
        if (
            getattr(self.workflow, "snapshotter", None) is not None
            and hasattr(self.workflow, "enable_emergency_snapshots")
            and not (self.args.dry_run or self.args.evaluate)
        ):
            # CLI runs own their process and have the SIGTERM/SIGINT
            # handlers installed: retain each epoch's start state so a
            # mid-epoch preemption snapshots consistently
            self.workflow.enable_emergency_snapshots()
        if self.args.dry_run:
            self.info("dry run: workflow initialized, skipping run()")
            return None
        if self.args.evaluate:
            import json

            import numpy as np

            split = self.args.evaluate
            try:
                # Workflow.evaluate rejects empty/misspelled splits (a
                # zero-sample evaluation would print a perfect score)
                result = self.workflow.evaluate(split, confusion=True)
            except ValueError as e:
                raise SystemExit(f"--evaluate: {e}") from None
            conf = result.pop("confusion", None)
            if conf is not None:
                result["confusion"] = np.asarray(conf).tolist()
            result["split"] = split
            print(json.dumps(result))
            self.result = result
            self._maybe_export()  # a restored model exports w/o training
            return self.result
        self.result = self.workflow.run()
        self._maybe_export()
        return self.result

    def _maybe_export(self) -> None:
        if not self.args.export:
            return
        import jax

        from znicz_tpu.export import export_model

        trained = self.workflow.model._replace(
            params=jax.device_get(self.workflow.state.params)
        )
        export_model(trained, self.args.export)
        self.info("exported trained model to %s", self.args.export)


def _child_argv(argv) -> list:
    """The supervised child's argv: the supervisor's own flags stripped,
    everything else (including ``--resume auto``, so every restart
    re-resolves the newest valid snapshot) passed through."""
    out, i = [], 0
    while i < len(argv):
        a = argv[i]
        base = a.split("=", 1)[0]
        if base in _SUPERVISOR_FLAGS:
            i += 2 if _SUPERVISOR_FLAGS[base] and "=" not in a else 1
            continue
        out.append(a)
        i += 1
    return out


def _atomic_json(path: str, obj) -> None:
    from znicz_tpu.services.web_status import _atomic_write

    _atomic_write(path, json.dumps(obj, indent=2))


def supervise(args: argparse.Namespace, argv) -> int:
    """The supervised auto-resume loop (docs/TRAINING.md).

    Runs ``python -m znicz_tpu <argv minus supervisor flags>`` as a
    child; exit 0 ends the run, a crash restarts it with exponential
    backoff while the ``--max-restarts`` budget lasts (each child gets
    ``ZNICZ_RESTARTS``/``ZNICZ_RESTART_BUDGET`` in its environment so
    its own ``/metrics`` carries ``znicz_train_restarts_total``), and a
    SIGTERM/SIGINT to the supervisor is forwarded to the child — whose
    graceful exit code (75) is then passed through instead of counting
    as a crash.  A child that exits 75 WITHOUT the supervisor being
    signalled (an externally-preempted child) is restarted like a
    crash: that is the auto-resume.  Restart history is written to
    ``supervisor.json`` next to the snapshots."""
    log = Logger()
    if args.resume != "auto" and not args.snapshot:
        log.warning(
            "--supervise without --resume auto: a restarted child "
            "starts FRESH instead of resuming from the newest snapshot"
        )
    child_cmd = [sys.executable, "-m", "znicz_tpu"] + _child_argv(argv)
    history: list = []
    state = {"proc": None, "signalled": None}
    history_dir = args.snapshot_dir or "."
    os.makedirs(history_dir, exist_ok=True)
    history_path = os.path.join(history_dir, "supervisor.json")

    def _forward(signum, frame):
        state["signalled"] = signum
        proc = state["proc"]
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signum)
            # child already reaped: nothing to forward to
            except OSError:  # znicz-check: disable=ZNC008
                pass

    prev = {}
    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[signum] = signal.signal(signum, _forward)
        # non-main thread (tests): forwarding off
        except ValueError:  # znicz-check: disable=ZNC008
            pass
    restarts = 0
    try:
        while True:
            env = dict(os.environ)
            env["ZNICZ_RESTARTS"] = str(restarts)
            env["ZNICZ_RESTART_BUDGET"] = str(args.max_restarts)
            log.info(
                "supervisor: starting child (restart %d/%d): %s",
                restarts, args.max_restarts, " ".join(child_cmd),
            )
            # own session: the terminal's Ctrl+C must not ALSO hit the
            # child directly — a doubled SIGINT would trip the child's
            # second-signal force-exit before the emergency snapshot.
            # The supervisor's forward is the one delivery.
            state["proc"] = subprocess.Popen(
                child_cmd, env=env, start_new_session=True
            )
            rc = state["proc"].wait()
            history.append(
                {
                    "restart": restarts,
                    "exit_code": rc,
                    "signalled": state["signalled"],
                    # timestamp, not a duration
                    "unix": time.time(),  # znicz-check: disable=ZNC007
                }
            )
            try:
                _atomic_json(
                    history_path,
                    {
                        "restarts": restarts,
                        "max_restarts": args.max_restarts,
                        "history": history,
                    },
                )
            except OSError:
                log.warning("supervisor.json write failed", exc_info=True)
            if rc == 0 or state["signalled"] is not None:
                # done, or the operator stopped US — pass the child's
                # code through (75 = graceful preemption with an
                # emergency snapshot on disk)
                return rc
            if restarts >= args.max_restarts:
                log.error(
                    "supervisor: restart budget (%d) spent; child exit "
                    "%d — giving up", args.max_restarts, rc,
                )
                return rc
            restarts += 1
            delay = (
                min(args.restart_backoff * 2 ** (restarts - 1), 60.0)
                if args.restart_backoff > 0
                else 0.0
            )
            log.warning(
                "supervisor: child exited %d; restart %d/%d in %.1fs",
                rc, restarts, args.max_restarts, delay,
            )
            if delay:
                time.sleep(delay)
            if state["signalled"] is not None:
                # a stop request landed while no child was alive (the
                # backoff window): honor it instead of spawning a
                # fresh child to train for hours after the operator
                # asked us to stop
                log.info(
                    "supervisor: stop requested during backoff; "
                    "not restarting"
                )
                return rc
    finally:
        for signum, handler in prev.items():
            try:
                signal.signal(signum, handler)
            # non-main thread: nothing was installed to restore
            except ValueError:  # znicz-check: disable=ZNC008
                pass


def _install_stop_handlers(launcher: Launcher) -> bool:
    """SIGTERM/SIGINT -> Workflow.request_stop(): drain the in-flight
    step, write the emergency snapshot, exit EXIT_PREEMPTED.  A second
    signal (or one before the workflow exists) exits immediately."""

    def _handler(signum, frame):
        wf = launcher.workflow
        if (
            wf is not None
            and hasattr(wf, "request_stop")
            and not getattr(wf, "_preempt_requested", False)
        ):
            wf.request_stop()
        else:
            raise SystemExit(EXIT_PREEMPTED)

    try:
        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)
        return True
    except ValueError:  # not the main thread (embedded/test use)
        return False


def _export_restart_telemetry() -> None:
    """Surface the supervisor-provided restart count/budget in THIS
    process's registry, so metrics.prom / status.json / the aggregator
    (and znicz-doctor's restart-loop gate) see them."""
    restarts = os.environ.get("ZNICZ_RESTARTS")
    budget = os.environ.get("ZNICZ_RESTART_BUDGET")
    if not restarts and not budget:
        return
    from znicz_tpu import observability
    from znicz_tpu.observability import pipeline as _pipeline

    try:
        n = int(restarts or 0)
        if n:
            observability.counter(
                _pipeline.RESTARTS_METRIC,
                "supervised training restarts preceding this process",
            ).inc(n)
        if budget:
            observability.gauge(
                _pipeline.RESTART_BUDGET_METRIC,
                "supervisor restart budget (--max-restarts)",
            ).set(float(int(budget)))
    except ValueError:
        Logger().warning(
            "malformed ZNICZ_RESTARTS/ZNICZ_RESTART_BUDGET ignored"
        )


def run_args(argv=None) -> Launcher:
    args = make_parser().parse_args(argv)
    # the CLI owns its process: force-install so --verbose wins even if
    # an imported library already touched the root logger
    setup_logging(10 if args.verbose else 20, force=True)
    if args.supervise:
        # the supervisor never builds a workflow itself — it loops the
        # SAME command (minus supervisor flags) as a child process
        raise SystemExit(
            supervise(args, list(sys.argv[1:] if argv is None else argv))
        )
    _export_restart_telemetry()
    if args.device:
        # jax is imported by the package before CLI parsing and deployment
        # sitecustomize hooks may force a platform config, so an explicit
        # --device must go through jax.config (env vars are already ignored
        # at this point).  MUST precede multihost.initialize(), which
        # touches jax.devices() and freezes the backend choice.
        import jax

        # "tpu,axon": force an accelerator — either the native TPU plugin or
        # a relay-registered one; errors out rather than silently using CPU.
        jax.config.update(
            "jax_platforms", "cpu" if args.device == "cpu" else "tpu,axon"
        )
    if args.coordinator or args.num_processes or args.process_id is not None:
        from znicz_tpu.parallel import multihost

        info = multihost.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
        Logger().info(
            "multi-host: process %d/%d, %d local / %d global devices",
            info["process_index"], info["process_count"],
            info["local_devices"], info["global_devices"],
        )
    if args.data_dir:
        root.common.update({"data_dir": args.data_dir})
    launcher = Launcher(args)
    sys.path.insert(0, os.path.dirname(os.path.abspath(args.workflow)))
    module = _load_module(args.workflow, "__znicz_workflow__")
    if args.config:
        _load_module(args.config, "__znicz_config__")
    if not hasattr(module, "run"):
        raise SystemExit(
            f"{args.workflow} does not define run(load, main) "
            "(reference workflow convention)"
        )
    if args.optimize:
        if args.evaluate:
            raise SystemExit(
                "--optimize and --evaluate conflict: the genetic search "
                "needs training runs, evaluation mode skips them"
            )
        from znicz_tpu.genetics import find_tunables, optimize_workflow

        # collect the search space BEFORE any probe: workflow modules may
        # materialize Tune copies into root during run(), and those must not
        # widen the genome
        tunables = find_tunables(root)
        # export must capture the BEST genome's weights, not whichever
        # candidate trained last: defer it past the search, then retrain
        # once with the winning config applied
        export_path, args.export = args.export, None
        if export_path:
            # exportability must fail BEFORE a long search, not after it:
            # probe with a dry run (builds the workflow, trains nothing);
            # restore the PRNG registry afterwards so the search trajectory
            # is identical with and without --export
            from znicz_tpu.core import prng as _prng

            prng_state = _prng.state_dict()
            args.export, args.dry_run, saved_dry = export_path, True, args.dry_run
            module.run(launcher.load, launcher.main)
            args.export, args.dry_run = None, saved_dry
            _prng.reset()
            _prng.load_state_dict(prng_state)
        launcher.result = optimize_workflow(
            module,
            launcher,
            generations=args.optimize,
            tunables=tunables,
            n_workers=args.optimize_workers,
        )
        if export_path:
            args.export = export_path
            opt_result = launcher.result
            module.run(launcher.load, launcher.main)
            launcher.result = opt_result  # keep the search summary
        return launcher
    from znicz_tpu.workflow.recovery import TrainingPreempted

    _install_stop_handlers(launcher)
    try:
        module.run(launcher.load, launcher.main)
    except TrainingPreempted as exc:
        Logger().info(
            "preempted gracefully (snapshot: %s); exiting %d",
            exc.snapshot_path, EXIT_PREEMPTED,
        )
        raise SystemExit(EXIT_PREEMPTED) from None
    return launcher


def main(argv=None) -> int:
    run_args(argv)
    return 0
