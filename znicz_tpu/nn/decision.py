"""Decision: epoch bookkeeping, best-model tracking, stop conditions.

Capability parity with ``znicz/decision.py`` (``DecisionGD``, ``DecisionMSE``)
[SURVEY.md 2.3 "Decision"]: accumulates per-split metrics across an epoch,
tracks the best validation result, decides when training stops
(``max_epochs`` reached, or ``fail_iterations`` epochs without validation
improvement), and tells the workflow when to snapshot ("on improved
validation", SURVEY.md 3.5/5.4).

This is deliberately host-side Python (the reference's Decision was a
gate-driven unit outside the hot kernels too); the jitted step only emits the
per-minibatch metric scalars this class consumes.
"""

from __future__ import annotations

from typing import Dict, List, Optional

TRAIN, VALID, TEST = "train", "valid", "test"


class EpochMetrics:
    """Accumulates additive metrics (n_err, loss*n, n_samples) over an epoch."""

    def __init__(self):
        self.n_samples = 0.0
        self.n_err = 0.0
        self.loss_sum = 0.0
        self.extras: Dict[str, float] = {}

    def add(self, metrics: Dict[str, float]) -> None:
        n = float(metrics.get("n_samples", 0.0))
        self.n_samples += n
        self.n_err += float(metrics.get("n_err", 0.0))
        self.loss_sum += float(metrics.get("loss", 0.0)) * n
        for k, v in metrics.items():
            if k in ("n_samples", "n_err", "loss"):
                continue
            try:
                v = float(v)
            # type filter: non-scalar extras (confusion matrix) are
            # intentionally not reduced here
            except (TypeError, ValueError):  # znicz-check: disable=ZNC008
                continue
            if k.startswith("max_"):  # peak-style metrics keep the max
                self.extras[k] = max(self.extras.get(k, float("-inf")), v)
            else:  # everything else is a sample-weighted epoch mean
                self.extras[k] = self.extras.get(k, 0.0) + v * n

    @property
    def loss(self) -> float:
        return self.loss_sum / max(self.n_samples, 1.0)

    def extras_summary(self) -> Dict[str, float]:
        return {
            k: v if k.startswith("max_") else v / max(self.n_samples, 1.0)
            for k, v in self.extras.items()
        }

    @property
    def err_pct(self) -> float:
        return 100.0 * self.n_err / max(self.n_samples, 1.0)


class Decision:
    """Stopping/bookkeeping policy driven by epoch-end metric reports.

    Usage per epoch: ``add_minibatch(split, metrics)`` for every step, then
    ``on_epoch_end(epoch)`` once — it returns a dict with ``improved`` (bool:
    validation got better; snapshot now) and ``stop`` (bool: training done).
    When there is no validation split, the train split drives improvement.
    """

    def __init__(
        self,
        *,
        max_epochs: Optional[int] = None,
        fail_iterations: int = 100,
        metric: str = "n_err",  # "n_err" (classification) or "loss" (MSE)
    ):
        self.max_epochs = max_epochs
        self.fail_iterations = fail_iterations
        self.metric = metric
        self.epoch = 0
        self.best_value: Optional[float] = None
        self.best_epoch = -1
        self.epochs_since_best = 0
        self.history: List[Dict[str, Dict[str, float]]] = []
        self._current: Dict[str, EpochMetrics] = {}

    def add_minibatch(self, split: str, metrics: Dict[str, float]) -> None:
        self._current.setdefault(split, EpochMetrics()).add(metrics)

    def _epoch_value(self) -> Optional[float]:
        src = self._current.get(VALID) or self._current.get(TRAIN)
        if src is None:
            return None
        return src.n_err if self.metric == "n_err" else src.loss

    def on_epoch_end(self, epoch: Optional[int] = None) -> Dict[str, object]:
        if epoch is not None:
            self.epoch = epoch
        summary = {
            split: {
                "n_samples": m.n_samples,
                "n_err": m.n_err,
                "err_pct": m.err_pct,
                "loss": m.loss,
                **m.extras_summary(),
            }
            for split, m in self._current.items()
        }
        self.history.append(summary)
        value = self._epoch_value()
        improved = False
        if value is not None and (
            self.best_value is None or value < self.best_value
        ):
            self.best_value = value
            self.best_epoch = self.epoch
            self.epochs_since_best = 0
            improved = True
        else:
            self.epochs_since_best += 1
        stop = self._would_stop(self.epoch, self.epochs_since_best)
        self._current = {}
        self.epoch += 1
        return {
            "improved": improved,
            "stop": stop,
            "summary": summary,
            "best_value": self.best_value,
            "best_epoch": self.best_epoch,
        }

    def _would_stop(self, epoch: int, epochs_since_best: int) -> bool:
        """THE stop predicate — on_epoch_end and can_stop_next_epoch must
        share it, or deferred epoch sync's exactness silently breaks when
        a stop condition is added to one but not the other."""
        return (
            self.max_epochs is not None and epoch + 1 >= self.max_epochs
        ) or (epochs_since_best >= self.fail_iterations)

    def can_stop_next_epoch(self) -> bool:
        """Whether the NEXT ``on_epoch_end`` could possibly return
        ``stop=True``, for ANY metric values (worst case: no improvement).
        Drives the workflow's deferred epoch sync: an epoch whose verdict
        provably cannot stop may be reported one epoch late without
        changing when training ends."""
        return self._would_stop(self.epoch, self.epochs_since_best + 1)

    # -- checkpointable state (host side of snapshot/resume, SURVEY.md 3.5) --
    def state_dict(self) -> Dict[str, object]:
        return {
            "epoch": self.epoch,
            "best_value": self.best_value,
            "best_epoch": self.best_epoch,
            "epochs_since_best": self.epochs_since_best,
            "history": self.history,
        }

    def load_state_dict(self, state: Dict[str, object]) -> None:
        self.epoch = state["epoch"]
        self.best_value = state["best_value"]
        self.best_epoch = state["best_epoch"]
        self.epochs_since_best = state["epochs_since_best"]
        self.history = list(state["history"])
