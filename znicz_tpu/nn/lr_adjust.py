"""Learning-rate schedules.

Capability parity with ``znicz/lr_adjust.py`` [SURVEY.md 2.3 "LR scheduling"]:
step/exponential/inverse decay policies applied to the GD units' learning
rate.  A policy here is a pure ``f(base_lr, step) -> lr`` callable; the
workflow evaluates it on the host each step and feeds the scalar into the
jitted train step (so no recompilation per change).
"""

from __future__ import annotations

import math
from typing import Callable, Dict

Policy = Callable[[float, int], float]


def constant() -> Policy:
    return lambda base_lr, step: base_lr


def step_decay(step_size: int, gamma: float = 0.1) -> Policy:
    """lr = base * gamma^(step // step_size) — the reference's StepExp."""
    return lambda base_lr, step: base_lr * gamma ** (step // step_size)


def exp_decay(gamma: float) -> Policy:
    """lr = base * gamma^step."""
    return lambda base_lr, step: base_lr * gamma**step


def inv_decay(gamma: float, power: float = 1.0) -> Policy:
    """lr = base * (1 + gamma*step)^-power — the reference's InvAdjustPolicy."""
    return lambda base_lr, step: base_lr * (1.0 + gamma * step) ** -power


def arbitrary(points) -> Policy:
    """Piecewise-constant from [(step_threshold, lr_multiplier), ...]
    (the reference's ArbitraryStepPolicy)."""
    pts = sorted(points)

    def f(base_lr: float, step: int) -> float:
        mult = 1.0
        for threshold, m in pts:
            if step >= threshold:
                mult = m
        return base_lr * mult

    return f


def linear_warmup_cosine(warmup: int, total: int, floor: float = 0.0) -> Policy:
    """TPU-era upgrade policy (not in reference): warmup + cosine decay."""

    def f(base_lr: float, step: int) -> float:
        if step < warmup:
            return base_lr * (step + 1) / max(warmup, 1)
        frac = min(1.0, (step - warmup) / max(total - warmup, 1))
        return floor + (base_lr - floor) * 0.5 * (1 + math.cos(math.pi * frac))

    return f


_NAMED: Dict[str, Callable[..., Policy]] = {
    "constant": constant,
    "step": step_decay,
    "exp": exp_decay,
    "inv": inv_decay,
    "arbitrary": arbitrary,
    "warmup_cosine": linear_warmup_cosine,
}


def get(name: str, **kwargs) -> Policy:
    """Build a named policy (config-file friendly)."""
    try:
        return _NAMED[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown lr policy {name!r}; have {sorted(_NAMED)}"
        ) from None
