"""SGD update rule with the reference's GradientDescentBase knobs.

Capability parity with ``znicz/nn_units.py`` ``GradientDescentBase`` and the
``gd*.py`` update math [SURVEY.md 2.3, 3.3]:

- ``learning_rate`` — base step size,
- ``gradient_moment`` — classical momentum on the accumulated update,
- ``weights_decay`` — L2 penalty folded into the gradient,
- ``l1_vs_l2`` — blend between L1 and L2 regularisation (reference exposes
  both; 0.0 = pure L2, 1.0 = pure L1),
- per-parameter multipliers (the reference lets bias run at a different lr
  via ``learning_rate_bias`` / ``weights_decay_bias``).

The reference computes these inside hand-written ``gradient_descent*.cl/.cu``
kernels per layer; here the whole update is one fused XLA expression over the
param pytree, executed inside the jitted train step.

Update rule (matching §3.3):
    v     <- moment * v - lr * (grad + decay_term(w))
    w     <- w + v
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp


class HyperParams(NamedTuple):
    """Per-layer (or global) update-rule knobs.

    Scalars may be Python floats (baked into the compiled program) or traced
    jnp scalars (for lr schedules fed in per step, see lr_adjust).
    """

    learning_rate: Any = 0.01
    gradient_moment: Any = 0.0
    weights_decay: Any = 0.0
    l1_vs_l2: Any = 0.0
    learning_rate_bias: Any = None  # default: same as learning_rate
    weights_decay_bias: Any = None  # default: same as weights_decay
    gradient_moment_bias: Any = None  # default: same as gradient_moment

    def for_param(self, name: str):
        """Resolve (lr, moment, decay, l1_vs_l2) for a named parameter."""
        is_bias = name.endswith("bias")
        lr = self.learning_rate
        wd = self.weights_decay
        moment = self.gradient_moment
        if is_bias and self.learning_rate_bias is not None:
            lr = self.learning_rate_bias
        if is_bias and self.weights_decay_bias is not None:
            wd = self.weights_decay_bias
        if is_bias and self.gradient_moment_bias is not None:
            moment = self.gradient_moment_bias
        return lr, moment, wd, self.l1_vs_l2


def _decay_term(w, wd, l1_vs_l2):
    # wd * ((1 - a) * w + a * sign(w)): L2 pulls proportionally, L1 by sign.
    if _is_zero(wd):
        return 0.0
    if _is_zero(l1_vs_l2):
        return wd * w
    return wd * ((1.0 - l1_vs_l2) * w + l1_vs_l2 * jnp.sign(w))


def _is_zero(x) -> bool:
    return isinstance(x, (int, float)) and x == 0


def update_param(w, grad, v, name: str, hyper: HyperParams):
    """One parameter's momentum-SGD update; returns (new_w, new_v)."""
    lr, moment, wd, l1l2 = hyper.for_param(name)
    g = grad + _decay_term(w, wd, l1l2)
    if _is_zero(moment):
        new_v = -lr * g
    else:
        new_v = moment * v - lr * g
    return w + new_v, new_v


def update_layer(params: dict, grads: dict, velocity: dict, hyper: HyperParams):
    """Update one layer's param dict ({'weights': ..., 'bias': ...})."""
    new_p, new_v = {}, {}
    for name in params:
        new_p[name], new_v[name] = update_param(
            params[name], grads[name], velocity[name], name, hyper
        )
    return new_p, new_v


def update(params, grads, velocity, hyper):
    """Update a whole model.

    ``params``/``grads``/``velocity`` are matching pytrees whose top level is a
    sequence of per-layer dicts; ``hyper`` is either one HyperParams applied
    globally or a sequence aligned with the layers (the reference's per-layer
    lr multipliers, SURVEY.md 2.3).
    """
    if isinstance(hyper, HyperParams):
        hyper = [hyper] * len(params)
    if len(hyper) != len(params):
        raise ValueError(
            f"hyper has {len(hyper)} entries for {len(params)} layers"
        )
    out_p, out_v = [], []
    for layer_p, layer_g, layer_v, h in zip(params, grads, velocity, hyper):
        if not layer_p:  # parameterless layer (pooling, activation, ...)
            out_p.append(layer_p)
            out_v.append(layer_v)
            continue
        new_p, new_v = update_layer(layer_p, layer_g, layer_v, h)
        out_p.append(new_p)
        out_v.append(new_v)
    return type(params)(out_p), type(velocity)(out_v)


def _leaf_name(path) -> str:
    for entry in reversed(path):
        if isinstance(entry, jax.tree_util.DictKey):
            return str(entry.key)
    return ""


def update_pytree(params, grads, velocity, hyper: HyperParams):
    """Name-aware update over an ARBITRARY pytree (bias rules keyed on the
    innermost dict key, like update_layer) — for models whose params are
    not a flat list of layer dicts, e.g. the pipelined transformer's
    stacked stage groups."""
    pairs = jax.tree_util.tree_map_with_path(
        lambda p, w, g, v: update_param(w, g, v, _leaf_name(p), hyper),
        params, grads, velocity,
    )
    is_pair = lambda t: isinstance(t, tuple)  # noqa: E731
    new_p = jax.tree_util.tree_map(lambda t: t[0], pairs, is_leaf=is_pair)
    new_v = jax.tree_util.tree_map(lambda t: t[1], pairs, is_leaf=is_pair)
    return new_p, new_v


def clip_gradients(grads, max_norm: Optional[float]):
    """Global-norm gradient clipping (upgrade knob; reference clips per-unit
    via ``gradient_*_with_clip`` variants [low confidence], exposed here as a
    single global norm)."""
    if not max_norm:
        return grads
    leaves = jax.tree_util.tree_leaves(grads)
    total = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
    scale = jnp.minimum(1.0, max_norm / (total + 1e-12))
    return jax.tree_util.tree_map(lambda g: g * scale, grads)
