"""NN training layer: the TPU-native equivalent of znicz's nn_units/gd plumbing.

The reference pairs every forward unit with a hand-written ``GradientDescent*``
unit carrying the update rule knobs (``learning_rate``, ``gradient_moment``,
``weights_decay``, per-layer multipliers) [SURVEY.md 2.3 "NN unit bases"].
Here the backward math is JAX autodiff and those knobs live in
:mod:`znicz_tpu.nn.optimizer`; :mod:`znicz_tpu.nn.evaluator` mirrors
``znicz/evaluator.py`` and :mod:`znicz_tpu.nn.decision` mirrors
``znicz/decision.py``.
"""

from znicz_tpu.nn import decision  # noqa: F401
from znicz_tpu.nn import evaluator  # noqa: F401
from znicz_tpu.nn import lr_adjust  # noqa: F401
from znicz_tpu.nn import optimizer  # noqa: F401
from znicz_tpu.nn.train_state import TrainState  # noqa: F401
