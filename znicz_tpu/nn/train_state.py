"""Pure-pytree train state.

The reference snapshots the entire mutable workflow object graph
(``veles/snapshotter.py``, SURVEY.md 3.5) — here the checkpointable training
state is an explicit immutable pytree, which is what makes jit/pjit, donation
and Orbax-style checkpointing work.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class TrainState(NamedTuple):
    """Everything the jitted train step reads and writes.

    params / velocity are matching pytrees (velocity is the momentum buffer,
    the reference's per-unit accumulated gradient with ``gradient_moment``).
    ``key`` seeds in-step randomness (dropout, stochastic pooling).
    """

    params: Any
    velocity: Any
    step: jnp.ndarray  # int32 scalar
    key: jax.Array

    @classmethod
    def create(cls, params, key) -> "TrainState":
        velocity = jax.tree_util.tree_map(jnp.zeros_like, params)
        return cls(
            params=params,
            velocity=velocity,
            step=jnp.zeros((), jnp.int32),
            key=key,
        )
