"""Evaluators: outputs + labels -> loss and metrics.

Capability parity with ``znicz/evaluator.py`` (``EvaluatorSoftmax``:
cross-entropy, n_err, confusion matrix, max_err_output_sum; ``EvaluatorMSE``)
[SURVEY.md 2.3 "Evaluators"].  In the reference the evaluator *emits
err_output* to seed the hand-written backward chain; here the loss scalar is
the autodiff seed, so each evaluator is a pure loss + metrics function used
inside the jitted step.

All functions take a ``mask`` (float [batch]) so the variable-size last
minibatch of an epoch is handled by masking inside jit instead of re-compiling
for a smaller batch (SURVEY.md §7 "Hard parts").
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp

from znicz_tpu.ops.all2all import log_softmax


def _norm_mask(mask: Optional[jnp.ndarray], batch: int):
    if mask is None:
        return jnp.ones((batch,), jnp.float32), float(batch)
    mask = mask.astype(jnp.float32)
    return mask, jnp.maximum(mask.sum(), 1.0)


def softmax(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    mask: Optional[jnp.ndarray] = None,
    n_classes: Optional[int] = None,
    compute_confusion: bool = False,
) -> Dict[str, jnp.ndarray]:
    """Cross-entropy over integer labels.

    Returns ``loss`` (mean CE over valid samples), ``n_err`` (int count of
    misclassifications — the reference's headline metric), ``max_err_y_sum``
    (largest |p - onehot| mass, the reference's saturation probe), and
    optionally ``confusion`` [n_classes, n_classes] (rows = truth).
    """
    mask, n_valid = _norm_mask(mask, logits.shape[0])
    logp = log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    loss = jnp.sum(nll * mask) / n_valid
    pred = jnp.argmax(logits, axis=1)
    err = (pred != labels).astype(jnp.float32) * mask
    out = {
        "loss": loss,
        "n_err": jnp.sum(err).astype(jnp.int32),
        "n_samples": n_valid,
    }
    p = jnp.exp(logp)
    onehot = jnp.zeros_like(p).at[jnp.arange(p.shape[0]), labels].set(1.0)
    out["max_err_y_sum"] = jnp.max(
        jnp.sum(jnp.abs(p - onehot), axis=1) * mask
    )
    if compute_confusion:
        nc = n_classes or logits.shape[-1]
        flat = labels * nc + pred
        out["confusion"] = jnp.zeros((nc * nc,), jnp.int32).at[flat].add(
            mask.astype(jnp.int32)
        ).reshape(nc, nc)
    return out


def mse(
    output: jnp.ndarray,
    target: jnp.ndarray,
    *,
    mask: Optional[jnp.ndarray] = None,
) -> Dict[str, jnp.ndarray]:
    """Mean-squared-error evaluator (EvaluatorMSE).

    Returns ``loss`` (mean over valid samples of per-sample mean square)
    and ``max_diff`` (largest absolute elementwise error).
    """
    mask, n_valid = _norm_mask(mask, output.shape[0])
    # flatten BEFORE subtracting: a flat model output vs a spatial target
    # (e.g. an MLP autoencoder reconstructing [H, W, C] images) must
    # compare by total feature count, not broadcast
    diff = output.reshape(output.shape[0], -1) - target.reshape(
        target.shape[0], -1
    )
    per_sample = jnp.mean(jnp.square(diff), axis=1)
    loss = jnp.sum(per_sample * mask) / n_valid
    # "loss" IS the mse; no duplicate key, so epoch aggregation (mean of
    # loss, max of max_*) can't disagree with itself.  rmse is derived by
    # consumers as sqrt(loss) at epoch granularity.
    return {
        "loss": loss,
        "max_diff": jnp.max(jnp.max(jnp.abs(diff), axis=1) * mask),
        "n_samples": n_valid,
    }
