"""znicz-tpu: a TPU-native rebuild of the VELES/Znicz training platform.

Capability reference: afcarl/veles.znicz (see SURVEY.md).  The execution model
is re-founded on JAX/XLA: units are pure ``init``/``apply`` functions, the hot
training loop is a single jit-compiled SPMD program, and the reference's
master-slave ZeroMQ data parallelism is replaced by sharded meshes with XLA
collectives over ICI (SURVEY.md section 2.5, 5.8).
"""

__version__ = "0.1.0"

from znicz_tpu.core.config import Config, root  # noqa: F401
from znicz_tpu.core.logger import Logger  # noqa: F401


# Lazy top-level API (PEP 562): keeps the heavyweight subsystems (workflow,
# parallel, services) — and, via prng, jax itself — out of a bare
# `import znicz_tpu`, so pure-stdlib consumers (the znicz-check CLI) run
# on hosts with no accelerator stack at all.
_LAZY = {
    "prng": ("znicz_tpu.core", "prng"),
    "Workflow": ("znicz_tpu.workflow", "Workflow"),
    "StandardWorkflow": ("znicz_tpu.workflow", "StandardWorkflow"),
    "KohonenWorkflow": ("znicz_tpu.workflow", "KohonenWorkflow"),
    "RBMWorkflow": ("znicz_tpu.workflow", "RBMWorkflow"),
    "TransformerLMWorkflow": ("znicz_tpu.workflow", "TransformerLMWorkflow"),
    "Snapshotter": ("znicz_tpu.workflow", "Snapshotter"),
    "FullBatchLoader": ("znicz_tpu.loader", "FullBatchLoader"),
    "ImageDirectoryLoader": ("znicz_tpu.loader", "ImageDirectoryLoader"),
    "DataParallel": ("znicz_tpu.parallel", "DataParallel"),
    "make_mesh": ("znicz_tpu.parallel", "make_mesh"),
    "Ensemble": ("znicz_tpu.ensemble", "Ensemble"),
    "export_model": ("znicz_tpu.export", "export_model"),
}


def __getattr__(name):
    import importlib

    if name in _LAZY:
        module, attr = _LAZY[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value  # cache: next access is a plain lookup
        return value
    raise AttributeError(f"module 'znicz_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY)))
