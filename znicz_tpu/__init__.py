"""znicz-tpu: a TPU-native rebuild of the VELES/Znicz training platform.

Capability reference: afcarl/veles.znicz (see SURVEY.md).  The execution model
is re-founded on JAX/XLA: units are pure ``init``/``apply`` functions, the hot
training loop is a single jit-compiled SPMD program, and the reference's
master-slave ZeroMQ data parallelism is replaced by sharded meshes with XLA
collectives over ICI (SURVEY.md section 2.5, 5.8).
"""

__version__ = "0.1.0"

from znicz_tpu.core.config import Config, root  # noqa: F401
from znicz_tpu.core import prng  # noqa: F401
from znicz_tpu.core.logger import Logger  # noqa: F401
