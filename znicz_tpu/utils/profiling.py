"""Tracing / profiling.

The reference has only per-unit wall-clock accumulation surfaced to the web
status page [SURVEY.md 5.1]; the rebuild upgrades to the jax profiler
(Perfetto/XProf traces of actual device execution) plus lightweight host-side
step timing that feeds the same status/metrics services.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Iterator, List, Optional


@contextlib.contextmanager
def trace(log_dir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a jax profiler trace (view with XProf/Perfetto/TensorBoard).

    Usage::

        with profiling.trace("/tmp/trace"):
            workflow.run_epoch()
    """
    import jax

    jax.profiler.start_trace(log_dir, host_tracer_level=host_tracer_level)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Stopwatch:
    """Wall-clock elapsed-seconds tracker.

    The one shared implementation of the run-lifetime bookkeeping that
    the status page, the run report and the training loop all need —
    monotonic (immune to NTP clock steps mid-run), resettable, and
    loggable without each consumer keeping its own ``t0`` arithmetic.
    """

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._t0 = time.monotonic()

    def elapsed(self) -> float:
        """Seconds since construction (or the last :meth:`reset`)."""
        return time.monotonic() - self._t0


class LatencyStats:
    """Order-statistics aggregate for per-request latencies.

    The serving engine (services/engine.py) records one sample per
    retired request; the summary is what the serve bench and status
    surfaces report.  Plain Python like the rest of this module — no
    numpy dependency for a handful of floats.

    Memory is BOUNDED: a ring buffer keeps the most recent
    ``max_samples`` observations (a long-lived engine must not grow a
    list forever), so percentiles/mean describe that sliding window
    while ``count`` stays the lifetime total.  ``observe`` (when given)
    is called once per recorded sample — the hook the engine uses to
    feed the shared metrics-registry histogram without keeping a second
    ledger beside it."""

    def __init__(
        self,
        max_samples: int = 4096,
        observe: Optional[Callable[[float], None]] = None,
    ):
        if max_samples < 1:
            raise ValueError(f"want max_samples >= 1; got {max_samples}")
        self._cap = int(max_samples)
        self._observe = observe
        self._samples: List[float] = []
        self._next = 0  # ring write cursor once the buffer is full
        self._count = 0

    def record(self, seconds: float) -> None:
        v = float(seconds)
        if self._observe is not None:
            self._observe(v)
        if len(self._samples) < self._cap:
            self._samples.append(v)
        else:
            self._samples[self._next] = v
            self._next = (self._next + 1) % self._cap
        self._count += 1

    def __len__(self) -> int:
        """Lifetime sample count (not the retained-window size)."""
        return self._count

    def summary(self) -> Dict[str, float]:
        if not self._samples:
            return {"count": 0}
        s = sorted(self._samples)

        def pct(p: float) -> float:
            return s[min(len(s) - 1, int(round(p * (len(s) - 1))))]

        return {
            "count": self._count,
            "mean_ms": 1000.0 * sum(s) / len(s),
            "p50_ms": 1000.0 * pct(0.5),
            "p95_ms": 1000.0 * pct(0.95),
            "p99_ms": 1000.0 * pct(0.99),
            "max_ms": 1000.0 * s[-1],
        }

    def reset(self) -> None:
        self._samples.clear()
        self._next = 0
        self._count = 0


class StepTimer:
    """Accumulate per-phase wall-clock times (the reference's per-unit timing
    ledger, SURVEY.md 5.1) without forcing device syncs: timings are host
    dispatch+block times and are meaningful at epoch granularity."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {
                "total_s": total,
                "count": self.counts[name],
                "mean_ms": 1000.0 * total / max(self.counts[name], 1),
            }
            for name, total in sorted(
                self.totals.items(), key=lambda kv: -kv[1]
            )
        }

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()
