"""Cross-cutting utilities: profiling/tracing, multi-host helpers."""

from znicz_tpu.utils.profiling import (  # noqa: F401
    StepTimer,
    trace,
)
