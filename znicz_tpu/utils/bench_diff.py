"""znicz-bench-diff: a machine-readable gate over two bench rounds.

The BENCH_*.json trajectory has always been read by humans; this tool
makes it a CI gate: compare two rounds per metric against a relative
threshold and exit non-zero on regression.

Accepted inputs (both sides independently):

* a bench-driver round file — one JSON object with a ``"parsed"`` dict
  of flattened numeric fields (the committed ``BENCH_rNN.json`` shape);
* raw ``python bench.py`` output — one JSON record per line, each
  carrying ``"metric"``/``"value"`` plus numeric extras (error records
  and non-numeric fields are skipped).

Direction is inferred per metric name — throughput-shaped names
(``*_per_sec``, ``*_rps``, ``*_hit_rate``, ``*_vs_baseline``,
``*_acceptance_rate``, ``*_bytes_per_second``, ``mfu``...) regress
when they DROP; latency/cost-shaped names (``*ttft*``, ``*latency*``,
``*_ms``, ``*compile*``, ``preemptions``, ``retries``, ``failed``,
``*_bound_frac``, ``*_rollbacks_total``, ``*_restarts_total``...)
regress when they RISE.  Override per metric with ``--lower NAME`` /
``--higher NAME``; scope with ``--only PREFIX``; tune with
``--threshold FRAC`` (default 0.10 — a 10% move).

Exit codes: 0 clean, 1 regression(s), 2 usage/parse error — the same
contract as ``tools/znicz-slo``.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Tuple

# substrings marking a metric where SMALLER is better.  Checked before
# the higher-better default, except that explicit throughput markers
# win (a name like lm_serve_frontdoor_ttft_p99_ms is lower-better; a
# name like lm_serve_tokens_per_sec is higher-better even though it
# contains "_sec").
_LOWER_MARKERS = (
    "ttft", "latency", "_ms", "step_ms", "wait", "compile",
    "preemption", "retries", "eviction", "failed", "error", "shed",
    "deadline", "cancelled", "queue_age", "lag", "_bound_frac",
    # self-healing: a round that rolled back / restarted / skipped
    # more than the baseline regressed, whatever its throughput says
    "rollback", "restart", "skipped",
)
_HIGHER_MARKERS = (
    "per_sec", "per_s", "rps", "hit_rate", "mfu", "concurrency",
    "vs_dense", "vs_baseline", "acceptance_rate", "bytes_per_second",
)

# fields of a record that are bookkeeping, not comparable metrics
_SKIP_KEYS = {"value", "n", "rc", "budget_s", "done_unix"}


def metric_direction(name: str, lower: set, higher: set) -> str:
    """``"higher"`` or ``"lower"`` (= which direction is BETTER)."""
    if name in lower:
        return "lower"
    if name in higher:
        return "higher"
    low = name.lower()
    if any(m in low for m in _HIGHER_MARKERS):
        return "higher"
    if any(m in low for m in _LOWER_MARKERS):
        return "lower"
    return "higher"


def _numeric(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def _absorb(record: dict, out: Dict[str, float]) -> None:
    """Flatten one bench record's numeric fields into the metric map
    (named metric first, numeric extras under their own key — the same
    merge the bench driver's ``parsed`` dict applies)."""
    name = record.get("metric")
    value = _numeric(record.get("value"))
    if isinstance(name, str) and value is not None:
        out[name] = value
    for key, v in record.items():
        if key in _SKIP_KEYS or key == "metric":
            continue
        fv = _numeric(v)
        if fv is not None:
            out[key] = fv


def load_metrics(path: str) -> Dict[str, float]:
    """Metric-name -> value for one round file (either accepted
    shape).  Raises ``ValueError`` when the file parses as neither."""
    with open(path) as f:
        text = f.read()
    out: Dict[str, float] = {}
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict):
        parsed = obj.get("parsed")
        _absorb(parsed if isinstance(parsed, dict) else obj, out)
        if not out:
            # a fully failed round (rc != 0, no parsed metrics — the
            # BENCH_r05 shape) must FAIL the gate, not pass it with
            # "0 compared"
            raise ValueError(
                f"{path}: no numeric metrics in this round "
                "(failed round?)"
            )
        return out
    # NDJSON: one record per line; error records skipped
    records = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as exc:
            raise ValueError(
                f"{path}: unparseable line {line[:80]!r}: {exc}"
            ) from exc
        if not isinstance(rec, dict):
            raise ValueError(f"{path}: line is not a JSON object")
        records += 1
        if "error" in rec:
            continue
        _absorb(rec, out)
    if not out:
        raise ValueError(
            f"{path}: no numeric metrics in this round "
            f"({records} record(s), all errors?)"
        )
    return out


def compare(
    old: Dict[str, float],
    new: Dict[str, float],
    *,
    threshold: float = 0.10,
    only: Optional[str] = None,
    lower: Optional[set] = None,
    higher: Optional[set] = None,
) -> Tuple[List[dict], List[str]]:
    """Per-metric comparison.  Returns ``(rows, missing)`` where each
    row carries the verdict; a metric in one round only is reported as
    missing, never a regression (sections come and go across rounds)."""
    lower = lower or set()
    higher = higher or set()
    rows: List[dict] = []
    names = sorted(set(old) | set(new))
    missing: List[str] = []
    for name in names:
        if only and not name.startswith(only):
            continue
        if name not in old or name not in new:
            missing.append(name)
            continue
        o, n = old[name], new[name]
        direction = metric_direction(name, lower, higher)
        if o == 0.0:
            # no base to take a ratio against: a lower-better metric
            # appearing from zero (compiles 0 -> 2) IS a regression;
            # higher-better from zero can only improve
            regressed = direction == "lower" and n > 0.0
            delta = None
        else:
            delta = (n - o) / abs(o)
            regressed = (
                delta < -threshold
                if direction == "higher"
                else delta > threshold
            )
        rows.append(
            {
                "metric": name,
                "old": o,
                "new": n,
                "delta_frac": round(delta, 4) if delta is not None else None,
                "direction": direction,
                "regressed": bool(regressed),
            }
        )
    return rows, missing


def _fmt_row(row: dict) -> str:
    delta = (
        f"{100.0 * row['delta_frac']:+.1f}%"
        if row["delta_frac"] is not None
        else "n/a"
    )
    mark = "REGRESSION" if row["regressed"] else "ok"
    arrow = "^" if row["direction"] == "higher" else "v"
    return (
        f"{row['metric']:<44} {row['old']:>12.4g} -> "
        f"{row['new']:>12.4g}  {delta:>8}  [{arrow}] {mark}"
    )


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    threshold = 0.10
    only = None
    as_json = False
    lower: set = set()
    higher: set = set()
    paths: List[str] = []
    i = 0
    try:
        while i < len(args):
            a = args[i]
            if a == "--threshold":
                threshold, i = float(args[i + 1]), i + 2
            elif a == "--only":
                only, i = args[i + 1], i + 2
            elif a == "--lower":
                lower.add(args[i + 1])
                i += 2
            elif a == "--higher":
                higher.add(args[i + 1])
                i += 2
            elif a == "--json":
                as_json, i = True, i + 1
            elif a.startswith("--"):
                raise IndexError(a)
            else:
                paths.append(a)
                i += 1
    except (IndexError, ValueError) as exc:
        print(f"znicz-bench-diff: bad arguments: {exc}", file=sys.stderr)
        return 2
    if len(paths) != 2 or threshold < 0:
        print(
            "usage: znicz-bench-diff OLD.json NEW.json "
            "[--threshold FRAC] [--only PREFIX] [--lower NAME] "
            "[--higher NAME] [--json]",
            file=sys.stderr,
        )
        return 2
    try:
        old = load_metrics(paths[0])
        new = load_metrics(paths[1])
    except (OSError, ValueError) as exc:
        print(f"znicz-bench-diff: {exc}", file=sys.stderr)
        return 2
    rows, missing = compare(
        old, new, threshold=threshold, only=only,
        lower=lower, higher=higher,
    )
    regressions = [r for r in rows if r["regressed"]]
    if as_json:
        print(
            json.dumps(
                {
                    "threshold": threshold,
                    "rows": rows,
                    "missing": missing,
                    "regressions": len(regressions),
                }
            )
        )
    else:
        for row in rows:
            print(_fmt_row(row))
        if missing:
            print(
                f"({len(missing)} metric(s) present in only one round: "
                + ", ".join(missing[:8])
                + (" ..." if len(missing) > 8 else "")
                + ")"
            )
        print(
            f"{len(rows)} compared, {len(regressions)} regression(s) "
            f"at threshold {threshold:.0%}"
        )
    return 1 if regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
