"""Fault injection: named failure points, toggled per-test or per-env.

The serving stack's failure handling (docs/SERVING.md "The front
door") is only trustworthy if every failure path actually RUNS in CI —
"the allocator could fail" is a theory until a test makes it fail and
asserts what the engine does next.  This module gives the engine and
the front door named injection points that are zero-cost no-ops in
production and deterministic failures under test:

========================  ==================================================
point                     effect when armed
========================  ==================================================
``engine.decode_step``    raises before the decode-chunk program runs (an
                          engine-thread crash: the watchdog-restart path)
``engine.prefill``        raises before a paged prefill chunk runs
``pool.alloc``            raises inside the block allocator (allocator
                          failure mid-tick)
``pool.pressure``         behavioral: the allocator reports the pool dry
                          (free list AND cache) — the eviction/preemption/
                          shedding ladder without filling real memory
``frontdoor.slow_tick``   sleeps at the top of the engine-thread tick (a
                          stalled tick: the watchdog-detection path;
                          also how SLO-breach latency is injected)
``pusher.push``           raises/sleeps inside a MetricsPusher push (a
                          dead or slow aggregator: the push failure
                          path — counted, logged, never propagated)
``router.connect``        raises as the cluster router opens a replica
                          connection (replica connect refused: the
                          route-to-next-best failover path)
``router.stream``         raises as the router reads one record of a
                          replica's token stream (mid-stream replica
                          death: the skip-prefix re-route path; arm
                          with ``after=k`` to let k records through
                          first)
``router.heartbeat``      raises inside a registry heartbeat probe
                          (heartbeat timeout: the ejection /
                          re-admission ladder without killing a real
                          server)
``loader.fetch``          fires inside the prefetch producer's timed
                          fetch of one batch (arm with ``delay=`` for
                          a deterministic SLOW PRODUCER: the
                          input-bound attribution fixture)
``loader.h2d``            fires inside the H2D probe's measured
                          region (arm with ``delay=`` for a slow
                          host->device link: the h2d-bound
                          attribution fixture)
``loader.fetch_flaky``    raises before each ``Loader.fill`` attempt (a
                          flaky data source: the bounded-retry /
                          skip-bad-batch ladder; arm with ``times=k``
                          so the k+1-th attempt succeeds)
``snapshot.write``        raises inside the snapshot file write, before
                          the atomic replace (disk failure: the
                          previous snapshot stays intact, ``maybe_save``
                          counts + continues)
``snapshot.load``         raises at the top of ``load_snapshot`` (an
                          unreadable checkpoint: ``find_latest_valid``
                          skips it, resume lands on an older one)
``train.step_nan``        behavioral: the workflow feeds the anomaly
                          detector a NaN loss for one step (arm with
                          ``flag=True``; ``after=k`` picks the step) —
                          the rollback path's fixture without poisoning
                          device state
``train.crash``           raises at the top of ``Workflow.run_epoch``
                          (a hard process crash at an epoch boundary;
                          arm with ``after=k`` to crash entering epoch
                          k: the supervised auto-resume fixture)
========================  ==================================================

Arming::

    with faults.injected("engine.decode_step", exc=RuntimeError("boom"),
                         times=1):
        ...          # exactly one decode chunk raises, then disarmed

    faults.inject("pool.pressure", flag=True)   # until faults.clear()
    faults.inject("frontdoor.slow_tick", delay=0.05)
    faults.inject("router.stream", after=2, times=1)  # 3rd read dies

or from the environment (process-wide, e.g. a chaos soak)::

    ZNICZ_FAULTS="engine.decode_step:times=1,frontdoor.slow_tick:delay=0.2"

Each spec is ``point[:field]...`` with fields ``times=<int>`` (default
unlimited), ``after=<int>`` (the first ``after`` fires pass through
untouched — how "die mid-stream, not at the start" is made
deterministic), ``delay=<seconds>`` and ``flag`` (behavioral: fire
just returns True); a point with none of ``exc``/``delay``/``flag``
raises :class:`FaultInjected` when fired.  The hot-path cost of an
UNARMED registry is one truthiness check on an empty dict.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterator, Optional

__all__ = [
    "FaultInjected",
    "inject",
    "clear",
    "fire",
    "armed",
    "injected",
]


class FaultInjected(RuntimeError):
    """The default exception an armed raise-point throws."""


class _Fault:
    __slots__ = ("name", "exc", "delay", "remaining", "after")

    def __init__(self, name: str, exc: Optional[BaseException],
                 delay: float, times: Optional[int], after: int):
        self.name = name
        self.exc = exc
        self.delay = float(delay)
        self.remaining = times  # None = until cleared
        self.after = int(after)  # fires to let through before acting


# module-level registry: empty in production, so fire() is one dict
# truthiness check on the hot path
_ARMED: Dict[str, _Fault] = {}
_LOCK = threading.Lock()


def inject(
    name: str,
    *,
    exc: Optional[BaseException] = None,
    delay: float = 0.0,
    times: Optional[int] = None,
    flag: bool = False,
    after: int = 0,
) -> None:
    """Arm ``name``.  ``exc`` raises at the point; ``delay`` sleeps
    there; ``flag`` arms a BEHAVIORAL point (``fire`` just returns
    True — e.g. ``pool.pressure`` reports the pool dry).  With none of
    the three, firing raises :class:`FaultInjected`.  ``times`` bounds
    how many fires before auto-disarm (None = until :func:`clear`);
    ``after`` lets the first N fires pass through untouched first —
    "the third stream read dies", not the first."""
    if exc is None and delay == 0.0 and not flag:
        exc = FaultInjected(f"injected fault at {name!r}")
    with _LOCK:
        _ARMED[name] = _Fault(name, exc, delay, times, after)


def clear(name: Optional[str] = None) -> None:
    """Disarm ``name`` (or every point when None).  Idempotent."""
    with _LOCK:
        if name is None:
            _ARMED.clear()
        else:
            _ARMED.pop(name, None)


def armed(name: str) -> bool:
    if not _ARMED:
        return False
    with _LOCK:
        return name in _ARMED


def fire(name: str) -> bool:
    """The injection point: no-op False when ``name`` is unarmed; when
    armed, sleeps ``delay`` and/or raises ``exc``, decrementing the
    remaining-fires budget, and returns True (behavioral points branch
    on it).  Thread-safe; auto-disarms once ``times`` is spent."""
    if not _ARMED:  # production fast path: one dict truthiness check
        return False
    with _LOCK:
        fault = _ARMED.get(name)
        if fault is None:
            return False
        if fault.after > 0:
            fault.after -= 1
            return False  # pass-through fire: not yet our turn
        if fault.remaining is not None:
            fault.remaining -= 1
            if fault.remaining <= 0:
                del _ARMED[name]
    if fault.delay:
        time.sleep(fault.delay)
    if fault.exc is not None:
        raise fault.exc
    return True


@contextlib.contextmanager
def injected(
    name: str,
    *,
    exc: Optional[BaseException] = None,
    delay: float = 0.0,
    times: Optional[int] = None,
    flag: bool = False,
    after: int = 0,
) -> Iterator[None]:
    """Scoped :func:`inject` — the point is disarmed on exit even if
    the body (or the fault itself) raised."""
    inject(name, exc=exc, delay=delay, times=times, flag=flag, after=after)
    try:
        yield
    finally:
        clear(name)


def _parse_env(spec: str) -> None:
    """``ZNICZ_FAULTS="a.b:times=1,c.d:delay=0.5"`` — malformed specs
    raise at import so a typo'd chaos config can't silently arm
    nothing."""
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        kwargs: Dict = {}
        for field in fields[1:]:
            key, _, value = field.partition("=")
            if key == "times":
                kwargs["times"] = int(value)
            elif key == "after":
                kwargs["after"] = int(value)
            elif key == "delay":
                kwargs["delay"] = float(value)
            elif key == "flag" and not value:
                kwargs["flag"] = True
            else:
                raise ValueError(
                    f"ZNICZ_FAULTS: unknown field {key!r} in {part!r} "
                    "(want times=<int>, after=<int>, delay=<seconds>, "
                    "or flag)"
                )
        inject(fields[0], **kwargs)


_ENV = os.environ.get("ZNICZ_FAULTS", "")
if _ENV:
    _parse_env(_ENV)
