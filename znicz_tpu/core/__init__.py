from znicz_tpu.core.config import Config, root  # noqa: F401
from znicz_tpu.core.logger import Logger  # noqa: F401


def __getattr__(name):
    # PEP 562: prng pulls in jax — load it on first use so pure-stdlib
    # consumers (the znicz-check CLI) can import the package on hosts
    # with no accelerator stack at all
    if name == "prng":
        import importlib

        module = importlib.import_module("znicz_tpu.core.prng")
        globals()[name] = module
        return module
    raise AttributeError(f"module 'znicz_tpu.core' has no attribute {name!r}")
