from znicz_tpu.core.config import Config, root  # noqa: F401
from znicz_tpu.core import prng  # noqa: F401
from znicz_tpu.core.logger import Logger  # noqa: F401
