"""Process-level task parallelism for searches and ensembles.

Capability parity with the reference's genuinely-parallel modes [SURVEY.md
2.5: ``veles/genetics/`` and ``veles/ensemble/`` ran many workflow instances
concurrently at process level].  Each worker process loads the workflow
module fresh (the reference ``run(load, main)`` two-file convention), seeds
the PRNG registry from its payload, trains, and returns a small result —
full isolation, so results are deterministic given seeds and independent of
worker count or completion order.

Workers inherit the parent environment: on a single accelerator, point the
search at the CPU backend (``--device cpu``) or the processes will contend
for the one chip; on CPU each worker is a true extra core-set.
"""

from __future__ import annotations

import os
import pickle
import sys
from typing import Any, Dict, List, Optional, Sequence


def _run_workflow_module(
    workflow_path: str,
    config_path: Optional[str],
    *,
    seed: Optional[int],
    stop_after: Optional[int],
    device: Optional[str] = None,
    genome: Optional[Sequence[float]] = None,
    dry_run: bool = False,
):
    """Load + run a workflow module the way the launcher does; returns
    (launcher, decision).  ``genome`` (optional) is applied to the config
    tree's Tune leaves after the module loads, before it runs."""
    from znicz_tpu.core.config import root
    from znicz_tpu.launcher import Launcher, _load_module, make_parser

    argv = [workflow_path] + ([config_path] if config_path else [])
    args = make_parser().parse_args(argv)
    args.random_seed = seed
    args.stop_after = stop_after
    args.dry_run = dry_run
    if device:
        import jax

        jax.config.update(
            "jax_platforms", "cpu" if device == "cpu" else "tpu,axon"
        )
    launcher = Launcher(args)
    sys.path.insert(0, os.path.dirname(os.path.abspath(workflow_path)))
    module = _load_module(workflow_path, "__znicz_workflow__")
    if config_path:
        _load_module(config_path, "__znicz_config__")
    if genome is not None:
        from znicz_tpu.genetics import find_tunables

        tunables = find_tunables(root)
        if len(tunables) != len(genome):
            raise ValueError(
                f"worker found {len(tunables)} Tune leaves but the genome "
                f"has {len(genome)} genes; the workflow module must "
                "register its tunables at import time"
            )
        for v, (node, key, _) in zip(genome, tunables):
            node[key] = v
    box: Dict[str, Any] = {}

    def load(cls, *a, **kw):
        return launcher.load(cls, *a, **kw)

    def main(**kw):
        box["decision"] = launcher.main(**kw)

    module.run(load, main)
    return launcher, box.get("decision")


def _worker_warn_shared_chip(payload: Dict[str, Any]) -> None:
    """In-worker twin of :func:`warn_if_shared_accelerator` for the case
    where the PARENT never initialized a backend (the normal CLI path —
    initializing one there just to warn would seize the TPU the workers
    need).  The caller tags exactly one payload with ``warn_n_workers``;
    this runs after the worker's own backend init, so the query is free."""
    n = payload.get("warn_n_workers")
    device = payload.get("device")
    if not n or device == "cpu":
        return
    import sys

    import jax

    try:
        if device:  # mirror _run_workflow_module's platform choice so the
            # warning probe initializes the SAME backend the run will use
            jax.config.update(
                "jax_platforms", "cpu" if device == "cpu" else "tpu,axon"
            )
        backend = jax.default_backend()
        n_chips = jax.device_count()
    except (ImportError, AttributeError, RuntimeError) as e:
        # best-effort probe over version-private jax API in the worker
        # bring-up path: a missing/renamed symbol (ImportError/
        # AttributeError) or an uninitializable backend (RuntimeError —
        # the very contention this would warn about) must never break
        # the run; anything else propagates
        import logging

        logging.getLogger(__name__).debug(
            "shared-chip warning probe failed: %s", e
        )
        return
    if backend in ("tpu", "axon") and n_chips < n:
        print(
            f"WARNING: {n} worker processes will contend for {n_chips} "
            "accelerator chip(s); pass device='cpu' (--device cpu) for "
            "concurrent evaluations on a shared chip",
            file=sys.stderr,
            flush=True,
        )


def eval_genome(payload: Dict[str, Any]) -> float:
    """Worker: one genetic-search evaluation; returns fitness (lower is
    better).  Payload keys: workflow, config, seed, stop_after, device,
    genome."""
    _worker_warn_shared_chip(payload)  # BEFORE the (possibly contended) run
    _, dec = _run_workflow_module(
        payload["workflow"],
        payload.get("config"),
        seed=payload.get("seed"),
        stop_after=payload.get("stop_after"),
        device=payload.get("device"),
        genome=payload["genome"],
    )
    if dec is None or dec.best_value is None:
        return float("inf")
    return float(dec.best_value)


def train_member(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Worker: train one ensemble member; pickles the trained params to
    ``payload['params_path']`` and returns {'best_value', 'params_path'}."""
    import jax

    _worker_warn_shared_chip(payload)  # BEFORE the (possibly contended) run
    launcher, dec = _run_workflow_module(
        payload["workflow"],
        payload.get("config"),
        seed=payload.get("seed"),
        stop_after=payload.get("stop_after"),
        device=payload.get("device"),
    )
    params = jax.device_get(launcher.workflow.state.params)
    with open(payload["params_path"], "wb") as f:
        pickle.dump(params, f)
    return {
        "best_value": None if dec is None else dec.best_value,
        "params_path": payload["params_path"],
    }


def warn_if_shared_accelerator(n_workers: int, device) -> bool:
    """Warn when N>1 spawned jax workers would target one accelerator
    chip (each re-initializes jax and contends for it); the documented
    recipe is device='cpu' / --device cpu for concurrent evaluations.
    Returns True when the warning fired (callers then skip the in-worker
    twin)."""
    if n_workers <= 1 or device == "cpu":
        return False
    import warnings

    try:
        # NEVER initialize a backend just to warn: on TPU the parent
        # would acquire the chip exclusively and the spawned workers
        # could no longer initialize it at all.  Only consult jax when
        # the parent already initialized it (then the query is free).
        from jax._src.xla_bridge import backends_are_initialized

        if not backends_are_initialized():
            return False
        import jax

        backend = jax.default_backend()
        n_chips = jax.device_count()
    except Exception:  # backend/private API unavailable
        return False
    if backend in ("tpu", "axon") and n_chips < n_workers:
        warnings.warn(
            f"{n_workers} worker processes will contend for {n_chips} "
            "accelerator chip(s); pass device='cpu' (--device cpu) for "
            "concurrent evaluations on a shared chip",
            stacklevel=3,
        )
        return True
    return False


def _call_with_parent_platforms(packed):
    """Worker trampoline: re-apply the PARENT's jax platform preference
    before any backend initializes.  A spawned interpreter re-runs any
    deployment sitecustomize, which may force an accelerator platform —
    a worker would then try to acquire (or hang waiting for) a device the
    parent deliberately avoided (e.g. tests pinned to CPU while a remote
    TPU relay is down).  The per-payload ``device`` override still wins:
    it is applied later, inside the workflow-module runner."""
    platforms, fn, payload = packed
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)
    return fn(payload)


def run_pool(fn, payloads: List[Dict[str, Any]], n_workers: int) -> list:
    """Map ``fn`` over payloads with n_workers spawned processes (order
    preserved).  n_workers<=1 still uses ONE worker process so results are
    identical to the concurrent path (fresh interpreter per evaluation
    semantics differ from in-process evaluation)."""
    import multiprocessing
    from concurrent.futures import ProcessPoolExecutor

    import jax

    # reading the config VALUE does not initialize a backend
    parent_platforms = jax.config.jax_platforms
    ctx = multiprocessing.get_context("spawn")
    # max_tasks_per_child=1: a FRESH interpreter per evaluation, so no
    # config-tree or PRNG state leaks between evaluations sharing a worker
    with ProcessPoolExecutor(
        max_workers=max(1, n_workers), mp_context=ctx, max_tasks_per_child=1
    ) as ex:
        return list(
            ex.map(
                _call_with_parent_platforms,
                [(parent_platforms, fn, p) for p in payloads],
            )
        )
