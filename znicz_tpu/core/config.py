"""Auto-vivifying configuration tree.

Capability parity with the reference's ``veles/config.py`` [SURVEY.md 2.1
"Config system"]: a global attribute tree ``root`` that config files (plain
Python modules) mutate, with deep ``update({...})`` merging.  Unlike the
reference, values can be validated/typed at workflow-build time and the tree
can be snapshotted to a plain dict for checkpointing.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Iterator


class Config:
    """A node in the auto-vivifying config tree.

    Attribute access on a missing name creates a child ``Config`` node, so
    configs can be written as ``root.mnist.learning_rate = 0.03`` without
    declaring intermediate nodes first.
    """

    __slots__ = ("__dict__", "_config_path_")

    def __init__(self, path: str = "") -> None:
        object.__setattr__(self, "_config_path_", path)

    # -- auto-vivification ------------------------------------------------
    def __getattr__(self, name: str) -> "Config":
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        child = Config(f"{self._config_path_}.{name}" if self._config_path_ else name)
        self.__dict__[name] = child
        return child

    def __setattr__(self, name: str, value: Any) -> None:
        self.__dict__[name] = value

    def __delattr__(self, name: str) -> None:
        del self.__dict__[name]

    # -- mapping-style access --------------------------------------------
    def __getitem__(self, name: str) -> Any:
        return getattr(self, name)

    def __setitem__(self, name: str, value: Any) -> None:
        setattr(self, name, value)

    def __contains__(self, name: str) -> bool:
        return name in self.__dict__

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def keys(self):
        return [k for k in self.__dict__ if not k.startswith("_")]

    def items(self):
        return [(k, self.__dict__[k]) for k in self.keys()]

    # -- deep update ------------------------------------------------------
    def update(self, tree: Dict[str, Any]) -> "Config":
        """Deep-merge a nested dict into this node (reference ``root.update``)."""
        if not isinstance(tree, dict):
            raise TypeError(f"Config.update expects a dict, got {type(tree)}")
        for key, value in tree.items():
            if isinstance(value, dict):
                node = self.__dict__.get(key)
                if not isinstance(node, Config):
                    node = Config(
                        f"{self._config_path_}.{key}" if self._config_path_ else key
                    )
                    self.__dict__[key] = node
                node.update(value)
            else:
                self.__dict__[key] = value
        return self

    # -- introspection ----------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key, value in self.items():
            out[key] = value.to_dict() if isinstance(value, Config) else value
        return out

    def get(self, name: str, default: Any = None) -> Any:
        """Non-vivifying lookup: returns ``default`` if unset or empty node."""
        value = self.__dict__.get(name, default)
        if isinstance(value, Config) and not value.keys():
            return default
        return value

    def copy(self) -> "Config":
        clone = Config(self._config_path_)
        clone.update(copy.deepcopy(self.to_dict()))
        return clone

    def clear(self) -> None:
        for key in list(self.keys()):
            del self.__dict__[key]

    def __repr__(self) -> str:
        return f"Config({self._config_path_!r}, {self.to_dict()!r})"


#: Global configuration root, mutated by config modules (two-file UX:
#: ``workflow.py`` + ``config.py`` overrides, reference veles/__main__.py).
root = Config("root")
