"""Compatibility shims over moving jax API surfaces.

The package targets the modern spelling of each API; this module maps
it onto older installs so one codebase runs everywhere the container
fleet does.  Keep each shim tiny, forward-first (new API when present),
and delete it when the fleet's floor moves past the old spelling.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with fallback to the pre-0.6 experimental home.

    jax >= 0.6 exposes ``jax.shard_map(..., check_vma=)``; older
    releases ship it as ``jax.experimental.shard_map.shard_map`` with
    the equivalent switch named ``check_rep`` — and the promotion and
    the kwarg rename did NOT land in the same release, so the kwarg is
    probed from the signature rather than inferred from the home.
    Call sites use the modern keyword; the shim translates.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    kwarg = "check_vma"
    try:
        import inspect

        if "check_vma" not in inspect.signature(sm).parameters:
            kwarg = "check_rep"
    # unintrospectable callable: keep the modern spelling
    except (TypeError, ValueError):  # znicz-check: disable=ZNC008
        pass
    return sm(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{kwarg: check_vma},
    )


def pcast(x, axis_name, *, to: str = "varying"):
    """``jax.lax.pcast`` with an identity fallback.

    The varying-manual-axes (vma) annotation only exists from jax 0.6;
    earlier shard_map has no vma tracking, so there is nothing to cast
    — the value itself is unchanged either way.
    """
    import jax.lax

    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to=to)
    return x
