"""Class-scoped logging mixin.

Capability parity with ``veles/logger.py`` [SURVEY.md 2.1 "Logger"]:
per-class loggers with a colored console formatter.  Structured key=value
metric emission is added for downstream metric writers.
"""

from __future__ import annotations

import logging
import sys
from typing import Any

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[1;31m",
}
_RESET = "\033[0m"
_configured = False


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


def setup_logging(level: int = logging.INFO) -> None:
    global _configured
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        _ColorFormatter("%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S")
    )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(level)
    _configured = True


class Logger:
    """Mixin giving every unit/workflow a class-scoped logger."""

    @property
    def logger(self) -> logging.Logger:
        if not _configured:
            setup_logging()
        return logging.getLogger(type(self).__name__)

    def debug(self, msg: str, *args: Any) -> None:
        self.logger.debug(msg, *args)

    def info(self, msg: str, *args: Any) -> None:
        self.logger.info(msg, *args)

    def warning(self, msg: str, *args: Any) -> None:
        self.logger.warning(msg, *args)

    def error(self, msg: str, *args: Any) -> None:
        self.logger.error(msg, *args)
