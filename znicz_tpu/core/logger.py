"""Class-scoped logging mixin.

Capability parity with ``veles/logger.py`` [SURVEY.md 2.1 "Logger"]:
per-class loggers with a colored console formatter.  Structured key=value
metric emission is added for downstream metric writers.
"""

from __future__ import annotations

import logging
import sys
from typing import Any

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[1;31m",
}
_RESET = "\033[0m"
_configured = False


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


def setup_logging(level: int = logging.INFO, *, force: bool = False) -> None:
    """Install the colored stderr handler on the root logger.

    Idempotent: when logging is already configured — by a prior call OR
    by any other library/test harness that attached root handlers —
    the existing handlers are left untouched (clobbering them silently
    un-configures everyone else).  The requested LEVEL is still
    applied (a multihost worker asking for INFO must not lose its logs
    to a default-WARNING root someone else left behind).  ``force=True``
    is the explicit escape hatch that replaces the handlers too.
    """
    global _configured
    root = logging.getLogger()
    if not force and (_configured or root.handlers):
        _configured = True  # someone configured logging; respect it
        if level < root.getEffectiveLevel():
            # only ever RAISE verbosity: a default-WARNING root must not
            # eat INFO logs, but a deliberately-DEBUG root (pytest
            # --log-cli-level, basicConfig) must not be quieted either
            root.setLevel(level)
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        _ColorFormatter("%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S")
    )
    root.handlers[:] = [handler]
    root.setLevel(level)
    _configured = True


class Logger:
    """Mixin giving every unit/workflow a class-scoped logger."""

    @property
    def logger(self) -> logging.Logger:
        if not _configured:
            setup_logging()
        return logging.getLogger(type(self).__name__)

    def debug(self, msg: str, *args: Any) -> None:
        self.logger.debug(msg, *args)

    def info(self, msg: str, *args: Any) -> None:
        self.logger.info(msg, *args)

    def warning(self, msg: str, *args: Any) -> None:
        self.logger.warning(msg, *args)

    def error(self, msg: str, *args: Any) -> None:
        self.logger.error(msg, *args)
