"""Named, deterministically seeded random generator registry.

Capability parity with ``veles/prng/`` [SURVEY.md 2.1 "PRNG"]: generators are
shared *by name* so that weight init, shuffling and dropout are reproducible
across runs and backends.  TPU-native twist: each generator owns a
``jax.random`` key and hands out fresh subkeys; inside jitted code keys are
threaded explicitly (they live in the train state), while host-side users
(weight init, loader shuffling) call the stateful convenience methods here.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np


class RandomGenerator:
    """A named stateful wrapper over a jax PRNG key chain."""

    def __init__(self, name: str, seed: Optional[int] = None):
        self.name = name
        self._seed = None
        self._key = None
        self._numpy = None
        self.seed(seed if seed is not None else _default_seed(name))

    def seed(self, value: int) -> None:
        self._seed = int(value)
        self._key = jax.random.key(self._seed)
        self._numpy = np.random.default_rng(self._seed)

    @property
    def initial_seed(self) -> int:
        return self._seed

    def key(self) -> jax.Array:
        """Return a fresh subkey; advances internal state."""
        self._key, sub = jax.random.split(self._key)
        return sub

    def keys(self, n: int) -> jax.Array:
        self._key, *subs = jax.random.split(self._key, n + 1)
        return jax.numpy.stack(subs)

    # -- host-side conveniences (numpy outputs, used outside jit) ---------
    def normal(self, shape, mean=0.0, stddev=1.0, dtype=np.float32) -> np.ndarray:
        return (self._numpy.standard_normal(shape) * stddev + mean).astype(dtype)

    def uniform(self, shape, low=-1.0, high=1.0, dtype=np.float32) -> np.ndarray:
        return self._numpy.uniform(low, high, shape).astype(dtype)

    def permutation(self, n: int) -> np.ndarray:
        return self._numpy.permutation(n)

    def integers(self, low, high, shape=()) -> np.ndarray:
        return self._numpy.integers(low, high, shape)

    # -- snapshot support (exact-resume contract, SURVEY.md 3.5) ----------
    def state_dict(self) -> dict:
        return {
            "seed": self._seed,
            "key": np.asarray(jax.random.key_data(self._key)),
            "key_impl": str(jax.random.key_impl(self._key)),
            "numpy_state": self._numpy.bit_generator.state,
        }

    def load_state_dict(self, state: dict) -> None:
        self._seed = state["seed"]
        impl = state.get("key_impl")
        self._key = jax.random.wrap_key_data(
            jax.numpy.asarray(state["key"]),
            **({"impl": impl} if impl else {}),
        )
        self._numpy = np.random.default_rng()
        self._numpy.bit_generator.state = state["numpy_state"]


_registry: Dict[str, RandomGenerator] = {}
_global_seed: Optional[int] = None


def _default_seed(name: str) -> int:
    # Stable cross-process default derived from the generator name; if a
    # global seed was set (``--random-seed``), derive from it so generators
    # created after seed_all() are seeded consistently with existing ones.
    if _global_seed is not None:
        return (_global_seed ^ hash_name(name)) % (2**31)
    return abs(hash_name(name)) % (2**31)


def hash_name(name: str) -> int:
    h = 0
    for ch in name:
        h = (h * 31 + ord(ch)) & 0x7FFFFFFF
    return h


def get(name: str = "default") -> RandomGenerator:
    """Return the shared generator registered under ``name`` (creating it)."""
    gen = _registry.get(name)
    if gen is None:
        gen = RandomGenerator(name)
        _registry[name] = gen
    return gen


def names() -> list:
    """Names of every currently registered generator."""
    return list(_registry)


def seed_all(seed: int) -> None:
    """Reseed every generator (current and future) from one master seed.

    Mirrors the reference's ``--random-seed`` flag behaviour: generator
    ``name`` gets ``seed ^ hash(name)`` so streams stay decorrelated.
    """
    global _global_seed
    _global_seed = int(seed)
    for name, gen in _registry.items():
        gen.seed((seed ^ hash_name(name)) % (2**31))


def state_dict() -> dict:
    """Capture every named generator's stream position (for snapshots)."""
    return {
        "global_seed": _global_seed,
        "generators": {n: g.state_dict() for n, g in _registry.items()},
    }


def load_state_dict(state: dict) -> None:
    """Restore generator streams captured by :func:`state_dict`; resumed
    runs draw the same shuffles/keys as the uninterrupted run."""
    global _global_seed
    _global_seed = state["global_seed"]
    for name, gen_state in state["generators"].items():
        get(name).load_state_dict(gen_state)


def reset() -> None:
    """Drop all registered generators and the global seed (test isolation)."""
    global _global_seed
    _global_seed = None
    _registry.clear()
