"""Model export for native (C++) deployment.

The reference ships ``libVeles``/``libZnicz`` — C++ engines that load trained
snapshots and run forward passes without Python [SURVEY.md 2.1 "libVeles",
2.3 "libZnicz", 2.4].  The rebuild's equivalent: export a trained model to a
self-describing binary file that ``native/znicz_infer`` (C++) executes on CPU
for deployment.

Format (little-endian):
    8 bytes   magic  "ZNICZT01"
    4 bytes   uint32 header_len
    N bytes   JSON header: {"input_shape": [...], "layers": [
                  {"type": ..., "config": {...},
                   "params": {"weights": {"shape": [...], "offset": B,
                              "size": n_floats}, ...}}]}
    ...       float32 parameter blobs at the stated byte offsets
              (relative to the end of the header)
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Optional

import numpy as np

MAGIC = b"ZNICZT01"

# layer types native/znicz_infer.cc implements; export refuses anything else
# so deployment failures surface BEFORE training, not at inference time
NATIVE_SUPPORTED_PREFIXES = (
    "all2all", "softmax", "conv", "max_pooling", "avg_pooling",
    "maxabs_pooling", "stochastic_pooling", "norm", "dropout", "activation_",
    "deconv", "cutter",
)

# forward-config keys the native engine understands, per layer type
_CONFIG_KEYS = (
    "kx", "ky", "sliding", "padding", "n_kernels", "n_channels",
    "alpha", "beta", "k", "n", "output_sample_shape", "n_output",
    "include_bias", "dropout_ratio",
)


def validate_exportable(model) -> None:
    """Raise ValueError when the model cannot run on the native engine —
    call this BEFORE training (the launcher's --export precheck does)."""
    if not hasattr(model, "layer_specs"):
        raise ValueError(
            "model has no layer_specs (not a layer-list Model); cannot "
            "export for the native engine"
        )
    unsupported = [
        spec["type"]
        for spec in model.layer_specs
        if not spec["type"].startswith(NATIVE_SUPPORTED_PREFIXES)
    ]
    if unsupported:
        raise ValueError(
            f"layer type(s) {sorted(set(unsupported))} are not implemented "
            "by the native inference engine (native/znicz_infer.cc); the "
            "exported artifact would fail at deployment"
        )
    for spec in model.layer_specs:
        if isinstance(spec.get("padding"), str):
            raise ValueError(
                f"layer {spec['type']!r} uses padding={spec['padding']!r}; "
                "native export needs explicit (left, top, right, bottom) "
                "padding — string padding depends on input size"
            )


def _write_artifact(
    path: str,
    input_shape,
    output_shape,
    output_kind: str,
    layer_arrays,
) -> Dict[str, Any]:
    """Serialize ``[(type, config, {name: array}), ...]`` to the ZNICZT01
    binary (shared by the layer-list and LM exporters)."""
    layers = []
    blobs = []
    offset = 0
    for ltype, config, params in layer_arrays:
        entry: Dict[str, Any] = {
            "type": ltype,
            "config": config,
            "params": {},
        }
        for name, value in params.items():
            arr = np.ascontiguousarray(np.asarray(value, np.float32))
            entry["params"][name] = {
                "shape": list(arr.shape),
                "offset": offset,
                "size": int(arr.size),
            }
            blobs.append(arr)
            offset += arr.nbytes
        layers.append(entry)
    header = {
        "format": 1,
        "input_shape": list(input_shape),
        "output_shape": list(output_shape),
        "output_kind": output_kind,
        "layers": layers,
    }
    payload = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<I", len(payload)))
        f.write(payload)
        for blob in blobs:
            f.write(blob.tobytes())
    return header


def export_model(model, path: str) -> Dict[str, Any]:
    """Write ``model`` (workflow.model.Model) to ``path``; returns header."""
    validate_exportable(model)
    layer_arrays = [
        (
            spec["type"],
            {key: _jsonable(spec[key]) for key in _CONFIG_KEYS if key in spec},
            params,
        )
        for spec, params in zip(model.layer_specs, model.params)
    ]
    return _write_artifact(
        path,
        model.input_shape,
        model.output_shape,
        # The ENGINE's output semantics, not the python model's: znicz_infer
        # applies softmax for a softmax head, so a softmax-headed model
        # (returns_logits in python) emits probabilities from the artifact.
        "probabilities" if model.returns_logits else "raw",
        layer_arrays,
    )


_LM_BLOCK_KEYS = (
    "ln1_scale", "ln1_bias", "wq", "wk", "wv", "wo",
    "ln2_scale", "ln2_bias", "w_up", "up_bias", "w_down", "down_bias",
)


def export_lm_model(
    params, path: str, *, n_heads: int, moe_top_k: Optional[int] = None
) -> Dict[str, Any]:
    """Export a transformer LM for the native engine (SURVEY.md 2.4: the
    beyond-parity flagship deploys the way every parity model does).

    ``params``: the flat ``init_lm_params`` layout
    ``[embed, block_0..L-1, head]`` (``TransformerLMWorkflow.state.params``
    for non-pipelined runs).  MoE blocks export too: ``moe_top_k`` is then
    REQUIRED and must match the training config — the engine gates with
    dense-dispatch semantics (every capacity-trained model serves fine
    dense-gated at inference; there is no token dropping to reproduce).
    Artifact I/O: input = [T] token ids stored as float32 in the raw
    file; output = [T, vocab] logits (``output_kind="raw"`` — matches
    python ``lm_apply``).
    """
    if not isinstance(params, (list, tuple)) or len(params) < 3:
        raise ValueError(
            "export_lm_model wants the flat [embed, blocks..., head] param "
            "list; pipelined (stacked-stage) params must be exported from "
            "a non-pipelined workflow"
        )
    embed, head, blocks = params[0], params[-1], params[1:-1]
    pos = np.asarray(embed["pos"])
    max_seq, d_model = pos.shape
    vocab = int(np.asarray(embed["embed"]).shape[0])
    layer_arrays = [
        ("lm_embed", {}, {"embed": embed["embed"], "pos": embed["pos"]})
    ]
    from znicz_tpu.workflow.transformer import MOE_KEY_MAP

    _FFN_KEYS = ("w_up", "up_bias", "w_down", "down_bias")
    for block in blocks:
        inner = int(np.asarray(block["wq"]).shape[1])
        if inner % n_heads:
            raise ValueError(
                f"block inner dim {inner} not divisible by n_heads {n_heads}"
            )
        config: Dict[str, Any] = {"n_heads": int(n_heads)}
        if "moe_router" in block:
            if moe_top_k is None or int(moe_top_k) < 1:
                # a silent default (or the engine's clamp of a degenerate
                # value) would gate differently than the model trained
                # with — the exact mismatch this kwarg prevents
                raise ValueError(
                    "this LM has mixture-of-experts blocks: pass "
                    "moe_top_k=<the training top_k, >= 1> so the native "
                    "engine gates identically"
                )
            config["top_k"] = int(moe_top_k)
            keys = [
                k for k in _LM_BLOCK_KEYS if k not in _FFN_KEYS
            ] + list(MOE_KEY_MAP)
        else:
            keys = list(_LM_BLOCK_KEYS)
        layer_arrays.append(
            ("lm_block", config, {k: block[k] for k in keys})
        )
    layer_arrays.append(("lm_head", {}, {"head": head["head"]}))
    return _write_artifact(
        path, [max_seq], [max_seq, vocab], "raw", layer_arrays
    )


def _jsonable(v):
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v
