"""Data-axis pool sharding for device-resident loaders.

The HBM pool shards over the mesh's DATA axis — each device holds 1/D of
every split, so dataset capacity is ``n_data x one chip's free HBM``
(max rows ~= n_data * HBM_free / bytes_per_sample) instead of one chip's.
Locality is by construction, so no collective ever moves pool-sized data:

- **Per-shard sampling.**  Each split is partitioned into D equal row
  blocks; batch position block ``s`` only draws from shard ``s``'s rows
  (every sample still appears exactly once per epoch — minibatch
  COMPOSITION mixes within shards instead of globally).
- **Local addresses.**  Minibatch payloads carry addresses into the
  owning device's pool block, and the gather/preproc runs inside a
  ``shard_map`` over the data axis.
- **Per-process placement.**  Multi-host jobs ship only their own shards'
  rows; ``DataParallel.shard_batch`` assembles the global pool array.

Mixin contract (see ``FullBatchLoader`` / ``ImageNetLoader``): subclasses
set ``self.wants_data_shards`` when the mode is on, implement
``_pool_split_arrays() -> {split: [n, ...] array}``, build payloads with
``_local_addr``, and wrap their per-shard preproc with
``_shard_map_pre``.  ``Workflow.initialize`` calls ``set_data_shards``
with the mesh's data-axis size before placing the device context.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from znicz_tpu.core.compat import shard_map
from znicz_tpu.loader.base import TRAIN, pool_offsets


class PoolShardedMixin:
    """Per-shard sampling + sharded pool placement (see module docstring)."""

    data_shards = 1

    def _pool_split_arrays(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    # -- shard layout --------------------------------------------------------
    def set_data_shards(self, n: int) -> None:
        """Partition every split into ``n`` equal row blocks (shard s of a
        split owns rows [s*len/n, (s+1)*len/n)); sampling becomes
        per-shard so batch position block s only references shard s."""
        if self.balanced:
            raise ValueError(
                "pool sharding is incompatible with balanced=True (the "
                "class-balanced shuffle is a global permutation; per-shard "
                "sampling owns the batch layout)"
            )
        bs = self.max_minibatch_size
        if bs % n:
            raise ValueError(
                f"pool sharding: minibatch_size {bs} not divisible by the "
                f"data axis {n}"
            )
        arrays = self._pool_split_arrays()
        for split, arr in arrays.items():
            if len(arr) % bs:
                raise ValueError(
                    f"pool sharding: split {split!r} has {len(arr)} rows, "
                    f"not a multiple of minibatch_size {bs} (static equal "
                    "per-shard chunks need full batches; pad or trim the "
                    "split)"
                )
        self.data_shards = int(n)
        self._order.clear()  # orders must be rebuilt in blocked layout
        # per-device block layout = the SHARED pool ordering contract
        # applied to one shard's chunk of each split
        self._local_split_offset = pool_offsets(
            {s: arr[: len(arr) // n] for s, arr in arrays.items()}
        )

    def _blocked_order(self, per_shard_rows: np.ndarray) -> np.ndarray:
        """[D, c] per-shard row ids -> epoch order where batch b's position
        block s holds shard s's rows [b*B/D, (b+1)*B/D)."""
        d, c = per_shard_rows.shape
        rows_per = self.max_minibatch_size // d
        steps = c // rows_per
        return (
            per_shard_rows.reshape(d, steps, rows_per)
            .transpose(1, 0, 2)
            .reshape(-1)
        )

    def _split_order(self, split: str) -> np.ndarray:
        if self.data_shards <= 1:
            return super()._split_order(split)
        n = self.class_lengths[split]
        order = self._order.get(split)
        if order is None or len(order) != n:
            c = n // self.data_shards
            order = self._blocked_order(
                np.arange(n).reshape(self.data_shards, c)
            )
            self._order[split] = order
        return order

    def reshuffle(self, split: str = TRAIN) -> None:
        if self.data_shards <= 1:
            return super().reshuffle(split)
        n = self.class_lengths.get(split, 0)
        if not n:
            return
        from znicz_tpu.core import prng

        gen = prng.get(self.rand_name)
        c = n // self.data_shards
        per_shard = np.stack(
            [s * c + gen.permutation(c) for s in range(self.data_shards)]
        )
        self._order[split] = self._blocked_order(per_shard)

    def _validate_batch_indices(self, idx: np.ndarray, split: str) -> None:
        if self.data_shards <= 1:
            return
        c = self.class_lengths[split] // self.data_shards
        rows_per = len(idx) // self.data_shards
        expected = np.repeat(np.arange(self.data_shards), rows_per)
        if not np.array_equal(idx // c, expected):
            raise AssertionError(
                "pool-sharded alignment violated: batch position block s "
                "must only reference data-axis shard s (a local gather "
                "would silently fetch wrong rows)"
            )

    def _local_addr(self, indices: np.ndarray, split: str) -> np.ndarray:
        """Dataset indices -> addresses within the owning device's pool
        block (split-chunk offset + position inside shard s's chunk)."""
        idx = np.asarray(indices, np.int64)
        c = self.class_lengths[split] // self.data_shards
        return (self._local_split_offset[split] + idx % c).astype(np.int32)

    # -- placement -----------------------------------------------------------
    def _local_pool(self) -> np.ndarray:
        """Shard-major pool rows owned by THIS process: for each of its
        data-axis shards, each split's chunk in the shared pool order
        (one allocation, filled in place — a transient 2x host copy would
        defeat this mode for exactly the huge datasets it targets)."""
        d = self.data_shards
        arrays = self._pool_split_arrays()
        lo = self.process_index * d // self.process_count
        hi = (self.process_index + 1) * d // self.process_count
        names = sorted(arrays)  # pool_offsets/pool_concat ordering contract
        chunk = {name: len(arrays[name]) // d for name in names}
        block = sum(chunk.values())
        first = arrays[names[0]]
        out = np.empty(
            ((hi - lo) * block,) + tuple(first.shape[1:]), first.dtype
        )
        row = 0
        for s in range(lo, hi):
            for name in names:
                c = chunk[name]
                out[row: row + c] = arrays[name][s * c:(s + 1) * c]
                row += c
        return out

    def place_device_context(self, parallel):
        if not self.wants_data_shards:
            return super().place_device_context(parallel)
        if parallel is None:
            raise ValueError(
                "pool-sharded loaders need parallel=DataParallel(mesh)"
            )
        if self.data_shards != parallel.n_data:
            raise ValueError(
                f"pool sharding: set_data_shards({parallel.n_data}) was "
                f"not applied (have {self.data_shards}); initialize the "
                "workflow instead of placing the context by hand"
            )
        self._mesh = parallel.mesh
        # shard the pool rows over the data axis: device_context() returns
        # ONLY this process's shards' rows (the one source of the sharded
        # pool layout), shard_batch assembles the global array
        # (make_array_from_process_local_data on multi-host).  Direct
        # jax.device_put(loader.device_context()) would place the local
        # block unsharded and break the local-address contract — always
        # place through here (Workflow.initialize does).
        return {"pool": parallel.shard_batch(self.device_context()["pool"])}

    def _shard_map_pre(self, per_shard_pre):
        """Wrap a per-shard ``pre(payload, pool_block) -> batch`` in a
        shard_map over the data axis (payload rows and pool rows both
        local; the preproc never leaves the device)."""
        import jax
        from jax.sharding import PartitionSpec as P

        from znicz_tpu.parallel.mesh import DATA_AXIS

        mesh = self._mesh
        spec = P(DATA_AXIS)

        def pre(payload, ctx):
            return shard_map(
                per_shard_pre,
                mesh=mesh,
                in_specs=(spec, spec),
                out_specs=spec,
            )(payload, ctx["pool"])

        return pre
