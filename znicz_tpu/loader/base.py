"""Loader protocol: split bookkeeping, shuffling, static-shape minibatches.

Reference semantics preserved (``veles/loader/base.py`` [SURVEY.md 2.1]):
three splits (test/valid/train), per-split sample counts, train reshuffled
every epoch from the shared named PRNG ("loader" generator), minibatch serving
with an explicit end-of-epoch signal.  Reference semantics *changed*: the
reference shrinks the last minibatch (``minibatch_size`` vs
``max_minibatch_size``); here the batch shape is static and a float mask marks
valid rows, because XLA recompiles on shape change.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Iterator, NamedTuple, Optional, Sequence

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.utils import faults

logger = logging.getLogger(__name__)

TRAIN, VALID, TEST = "train", "valid", "test"
SPLITS = (TRAIN, VALID, TEST)


class LoaderFetchError(RuntimeError):
    """A minibatch fetch (``Loader.fill``) kept failing past the retry
    budget — the typed, consumer-visible form of a flaky data source
    (docs/TRAINING.md "Self-healing training")."""


def _loader_retry_counter():
    from znicz_tpu import observability
    from znicz_tpu.observability import pipeline as _pipeline

    return observability.counter(
        _pipeline.LOADER_RETRIES_METRIC,
        "minibatch fetch attempts retried after a transient failure",
    )


def _loader_skipped_counter():
    from znicz_tpu import observability
    from znicz_tpu.observability import pipeline as _pipeline

    return observability.counter(
        _pipeline.LOADER_SKIPPED_METRIC,
        "minibatches dropped after exhausting fetch retries "
        "(skip_bad_batches=True)",
    )


class Minibatch(NamedTuple):
    data: np.ndarray  # [max_minibatch_size, ...]  padded
    labels: Optional[np.ndarray]  # [max_minibatch_size] int32, or None
    targets: Optional[np.ndarray]  # regression/AE targets, or None
    mask: np.ndarray  # [max_minibatch_size] float32, 1.0 = valid row
    indices: np.ndarray  # dataset indices backing each row (padding repeats)


class Loader:
    """Abstract loader. Subclasses implement ``fill(indices, split)``.

    ``class_lengths``: dict split -> number of samples (0 = split absent).
    """

    def __init__(
        self,
        *,
        minibatch_size: int = 100,
        shuffle: bool = True,
        balanced: bool = False,
        rand_name: str = "loader",
        fetch_retries: int = 2,
        fetch_backoff_s: float = 0.05,
        skip_bad_batches: bool = False,
    ):
        self.max_minibatch_size = int(minibatch_size)
        self.shuffle = shuffle
        self.balanced = balanced  # spread classes evenly across minibatches
        self.rand_name = rand_name
        # fault tolerance (docs/TRAINING.md): fill(indices, split) is a
        # pure function of its indices, so a transient failure (network
        # FS hiccup, flaky decoder) is retried with bounded backoff;
        # past the budget the batch is either SKIPPED (counted, masked
        # out of the epoch — skip_bad_batches=True) or surfaces as the
        # typed LoaderFetchError.  The loader.fetch_flaky fault point
        # fires before each attempt (CI fixture for both paths).
        self.fetch_retries = int(fetch_retries)
        self.fetch_backoff_s = float(fetch_backoff_s)
        self.skip_bad_batches = bool(skip_bad_batches)
        self._order: Dict[str, np.ndarray] = {}
        self.epoch_number = 0
        # multi-host sample shard (Loader.set_process_shard): this process
        # serves only its contiguous row range of every global minibatch
        self.process_index = 0
        self.process_count = 1

    # -- subclass interface ------------------------------------------------
    @property
    def class_lengths(self) -> Dict[str, int]:
        raise NotImplementedError

    @property
    def sample_shape(self) -> tuple:
        """Per-sample data shape (no batch dim) — drives model shape
        inference in StandardWorkflow."""
        raise NotImplementedError

    def fill(self, indices: np.ndarray, split: str) -> Minibatch:
        """Materialize the samples at ``indices`` of ``split``."""
        raise NotImplementedError

    def split_labels(self, split: str) -> Optional[np.ndarray]:
        """All labels of a split (enables ``balanced``); None if unknown."""
        return None

    def device_preproc(self):
        """Optional jit-safe callable ``pre(x, ctx)`` applied to the batch
        ON DEVICE inside the compiled step (e.g. u8 -> f32 affine + mean
        subtraction, or an HBM-pool gather).  Lets ``fill`` return uint8
        minibatches (4x smaller host->device transfer) or bare index vectors
        (device-resident datasets); the convert fuses into the XLA program.
        ``ctx`` is the device-side pytree from :meth:`device_context` (None
        when unused).  None = batches arrive ready."""
        return None

    def device_context(self):
        """Host pytree of large loader-owned arrays the preproc needs on
        device (e.g. the device-resident dataset pool).  The workflow
        device_puts it ONCE at initialize and threads it through the jitted
        step as an ARGUMENT — never a closure constant, which XLA would
        embed into the compiled executable."""
        return None

    # data-axis pool sharding (loaders that support it set this True and
    # implement set_data_shards; Workflow.initialize calls it with the
    # mesh's data-axis size before placing the device context)
    wants_data_shards = False

    def set_data_shards(self, n: int) -> None:
        raise NotImplementedError

    def place_device_context(self, parallel):
        """Device-place :meth:`device_context` (None when there is none).
        Default: fully replicated over the mesh — loaders whose context is
        sharded (e.g. the data-axis-sharded pool) override this."""
        ctx = self.device_context()
        if ctx is None:
            return None
        import jax

        if parallel is not None:
            return jax.tree_util.tree_map(parallel.put_replicated, ctx)
        return jax.tree_util.tree_map(jax.device_put, ctx)

    def set_process_shard(self, index: int, count: int) -> None:
        """Multi-host sample sharding (the reference's job-assignment
        semantics, SURVEY.md 3.4: the master handed each slave an index
        range; here every process derives its own range deterministically).

        All processes compute the IDENTICAL global epoch order (the named
        PRNG is seeded the same everywhere), then each serves only rows
        ``[index*B/count, (index+1)*B/count)`` of every global minibatch —
        exactly the rows its addressable devices own under data-parallel
        sharding, so ``DataParallel.shard_batch`` can assemble the global
        batch with zero cross-host data movement."""
        if not 0 <= index < count:
            raise ValueError(f"process {index} outside [0, {count})")
        if self.max_minibatch_size % count:
            raise ValueError(
                f"minibatch_size {self.max_minibatch_size} not divisible "
                f"by process_count {count}"
            )
        if count > 1 and self.skip_bad_batches:
            # a skip is per-process: one process dropping a batch while
            # its peers dispatch the step desynchronizes the collective
            # and hangs the fleet — fail loudly at configuration time
            raise ValueError(
                "skip_bad_batches=True cannot combine with multi-host "
                "training (a per-process skip desynchronizes step "
                "counts across processes); use fetch_retries instead"
            )
        self.process_index = int(index)
        self.process_count = int(count)

    # -- serving -----------------------------------------------------------
    def n_minibatches(self, split: str) -> int:
        n = self.class_lengths.get(split, 0)
        return -(-n // self.max_minibatch_size) if n else 0

    def _split_order(self, split: str) -> np.ndarray:
        n = self.class_lengths[split]
        order = self._order.get(split)
        if order is None or len(order) != n:
            order = np.arange(n)
            self._order[split] = order
        return order

    def reshuffle(self, split: str = TRAIN) -> None:
        n = self.class_lengths.get(split, 0)
        if not n:
            return
        gen = prng.get(self.rand_name)
        labels = self.split_labels(split) if self.balanced else None
        if labels is None:
            self._order[split] = gen.permutation(n)
            return
        # class-balanced shuffle (reference "class-balanced offsets",
        # SURVEY.md §7): shuffle within each class, then place sample ranked
        # r of a size-m class at fractional position (r + jitter)/m so every
        # minibatch sees a near-proportional class mix
        labels = np.asarray(labels)
        keys = np.empty(n, np.float64)
        for cls in np.unique(labels):
            idx = np.flatnonzero(labels == cls)
            perm = idx[gen.permutation(len(idx))]
            jitter = gen.uniform((len(idx),), 0.0, 1.0)
            keys[perm] = (np.arange(len(idx)) + jitter) / len(idx)
        self._order[split] = np.argsort(keys, kind="stable")

    def batches(
        self, split: str, *, shuffle: Optional[bool] = None
    ) -> Iterator[Minibatch]:
        """Yield padded fixed-shape minibatches covering the split once.

        ``shuffle=False`` serves the current order WITHOUT drawing from the
        shuffle PRNG stream — evaluation passes must be read-only so they
        don't desynchronize resume determinism.
        """
        n = self.class_lengths.get(split, 0)
        if not n:
            return
        if shuffle is None:
            shuffle = split == TRAIN and self.shuffle
        if shuffle:
            self.reshuffle(split)
        order = self._split_order(split)
        bs = self.max_minibatch_size
        # multi-host: this process fills only its contiguous row range of
        # each global minibatch (mask is computed globally, then sliced, so
        # padding rows stay masked no matter which process holds them)
        lo = self.process_index * bs // self.process_count
        hi = (self.process_index + 1) * bs // self.process_count
        for start in range(0, n, bs):
            idx = order[start : start + bs]
            n_valid = len(idx)
            if n_valid < bs:  # pad by repeating the first index; mask it out
                pad = np.full(bs - n_valid, idx[0] if n_valid else 0)
                idx = np.concatenate([idx, pad])
            mask = np.zeros(bs, np.float32)
            mask[:n_valid] = 1.0
            self._validate_batch_indices(idx, split)
            if self.process_count > 1:
                idx, mask = idx[lo:hi], mask[lo:hi]
            mb = self._fill_with_retry(idx, split)
            if mb is None:  # skipped bad batch (counted)
                continue
            yield mb._replace(mask=mask, indices=idx)

    def _fill_with_retry(self, idx: np.ndarray, split: str):
        """``fill`` behind the retry/skip ladder.  Returns None for a
        skipped batch (``skip_bad_batches``); raises the typed
        :class:`LoaderFetchError` once the retry budget is spent."""
        attempt = 0
        while True:
            try:
                faults.fire("loader.fetch_flaky")
                return self.fill(idx, split)
            except Exception as exc:
                if attempt >= self.fetch_retries:
                    if self.skip_bad_batches:
                        _loader_skipped_counter().inc()
                        logger.warning(
                            "skipping bad %s batch after %d attempt(s): "
                            "%s", split, attempt + 1, exc,
                        )
                        return None
                    raise LoaderFetchError(
                        f"fetching a {split} minibatch failed "
                        f"{attempt + 1} time(s): {exc}"
                    ) from exc
                attempt += 1
                _loader_retry_counter().inc()
                logger.warning(
                    "%s minibatch fetch failed (attempt %d/%d): %s — "
                    "retrying", split, attempt, self.fetch_retries + 1,
                    exc,
                )
                if self.fetch_backoff_s > 0:
                    time.sleep(
                        self.fetch_backoff_s * (2 ** (attempt - 1))
                    )

    def _validate_batch_indices(self, idx: np.ndarray, split: str) -> None:
        """Hook: loaders with placement invariants on the FULL (pre-
        process-slice) batch index layout check them here (e.g. the
        pool-sharded alignment of batch blocks to data-axis shards)."""

    def epoch(self) -> Iterator[tuple]:
        """One full epoch: train batches then valid then test, tagged."""
        for split in (TRAIN, VALID, TEST):
            for mb in self.batches(split):
                yield split, mb
        self.epoch_number += 1

    # -- snapshot support ----------------------------------------------------
    def state_dict(self) -> dict:
        return {
            "epoch_number": self.epoch_number,
            "order": {k: v.copy() for k, v in self._order.items()},
            # shuffle-stream position, so a resumed run draws the same
            # permutations as the uninterrupted one (SURVEY.md 3.5)
            "prng": prng.get(self.rand_name).state_dict(),
        }

    def load_state_dict(self, state: dict) -> None:
        self.epoch_number = state["epoch_number"]
        self._order = {k: np.asarray(v) for k, v in state["order"].items()}
        if "prng" in state:
            prng.get(self.rand_name).load_state_dict(state["prng"])


def pool_offsets(splits: Dict[str, "np.ndarray"]) -> Dict[str, int]:
    """Row offset of each split inside the device-resident pool.  The ONE
    ordering contract shared with :func:`pool_concat` — device-resident
    loaders must never maintain it independently."""
    offsets, off = {}, 0
    for s in sorted(splits):
        offsets[s] = off
        off += len(splits[s])
    return offsets


def pool_concat(splits: Dict[str, "np.ndarray"]) -> np.ndarray:
    """Concatenate split arrays in :func:`pool_offsets` order (transient
    host copy; callers device_put it and drop the reference)."""
    return np.concatenate([np.asarray(splits[s]) for s in sorted(splits)])


def split_sizes(n: int, fractions: Sequence[float]) -> Dict[str, int]:
    """Partition ``n`` samples into train/valid/test by fractions
    (train gets the remainder)."""
    valid = int(n * fractions[0]) if len(fractions) > 0 else 0
    test = int(n * fractions[1]) if len(fractions) > 1 else 0
    return {TRAIN: n - valid - test, VALID: valid, TEST: test}
