"""ImageNet-style training pipeline: pack, crop, flip, device-side normalize.

Capability parity with the reference ImageNet preprocessing pipeline
(``znicz/loader/`` + ``znicz/samples/ImageNet/`` preparation scripts
[SURVEY.md 2.3 "Znicz loaders", "Samples"]): resize to a canonical size,
train-time random crop + horizontal flip, mean subtraction, eval center
crop.  Re-founded TPU-first:

- **Pack once, stream forever.**  ``pack_image_dir`` converts a directory
  tree (``train/<class>/*.jpg``) into per-split ``.npy`` u8 arrays (short
  side resized, center-cropped to ``size``x``size``).  The loader memory-maps
  them, so datasets larger than host RAM stream from disk.
- **Crops are native.**  Per-minibatch random crop + flip runs in
  ``native/batch_assembler.cc`` (``crop_gather_u8``) — a parallel memcpy,
  not a Python loop.
- **Normalization is on-device.**  Minibatches cross host->device as u8
  (4x fewer bytes than f32); the affine u8->f32 + channel-mean subtraction
  happens inside the jitted step (``device_preproc``), where XLA fuses it
  into the first convolution's input.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.loader.base import (
    SPLITS,
    TRAIN,
    Loader,
    Minibatch,
    pool_concat as base_pool_concat,
    pool_offsets as base_pool_offsets,
)
from znicz_tpu.loader.pool_sharded import PoolShardedMixin
from znicz_tpu.loader.image import IMAGE_EXTENSIONS, _read_image

MEAN_FILE = "mean_rgb.json"
CLASSES_FILE = "classes.json"


def _resize_short_side(img: np.ndarray, size: int) -> np.ndarray:
    """Nearest-neighbor resize so the SHORT side equals ``size`` (aspect
    preserved) — the reference pipeline's canonicalization step."""
    h, w = img.shape[:2]
    if h <= w:
        nh, nw = size, max(size, int(round(w * size / h)))
    else:
        nh, nw = max(size, int(round(h * size / w))), size
    rows = np.minimum((np.arange(nh) * h / nh).astype(np.int64), h - 1)
    cols = np.minimum((np.arange(nw) * w / nw).astype(np.int64), w - 1)
    return img[rows][:, cols]


def _center_crop(img: np.ndarray, size: int) -> np.ndarray:
    h, w = img.shape[:2]
    oy, ox = (h - size) // 2, (w - size) // 2
    return img[oy : oy + size, ox : ox + size]


def _to_u8_rgb(img: np.ndarray, size: int) -> np.ndarray:
    """Decode-normalized float image (0..1) -> canonical [size, size, 3] u8."""
    img = _center_crop(_resize_short_side(img, size), size)
    if img.shape[-1] == 1:
        img = np.repeat(img, 3, axis=-1)
    return np.clip(img * 255.0 + 0.5, 0, 255).astype(np.uint8)


def pack_image_dir(
    src_dir: str, out_dir: str, *, size: int = 256, verbose: bool = False
) -> Dict[str, int]:
    """One-time preparation: directory tree -> packed u8 .npy per split.

    Input layout (reference convention): ``src_dir/<split>/<class>/*.png``.
    Writes ``<split>_images.npy`` ([n, size, size, 3] u8),
    ``<split>_labels.npy`` ([n] int32), ``classes.json`` and
    ``mean_rgb.json`` (channel means of the train split, 0..1 units).
    Returns per-split sample counts.
    """
    os.makedirs(out_dir, exist_ok=True)
    classes: list = []
    counts: Dict[str, int] = {}
    mean_acc, mean_n = np.zeros(3, np.float64), 0
    for split in SPLITS:
        split_dir = os.path.join(src_dir, split)
        if not os.path.isdir(split_dir):
            continue
        entries = []
        for cls in sorted(os.listdir(split_dir)):
            cls_dir = os.path.join(split_dir, cls)
            if not os.path.isdir(cls_dir):
                continue
            files = [
                os.path.join(cls_dir, f)
                for f in sorted(os.listdir(cls_dir))
                if f.lower().endswith(IMAGE_EXTENSIONS)
            ]
            if not files:
                continue
            if cls not in classes:
                classes.append(cls)
            entries.extend((p, classes.index(cls)) for p in files)
        if not entries:
            continue
        # np.lib.format + open_memmap: write incrementally, never hold the
        # whole split in RAM
        from numpy.lib.format import open_memmap

        images = open_memmap(
            os.path.join(out_dir, f"{split}_images.npy"),
            mode="w+", dtype=np.uint8, shape=(len(entries), size, size, 3),
        )
        labels = np.empty(len(entries), np.int32)
        for i, (path, label) in enumerate(entries):
            images[i] = _to_u8_rgb(_read_image(path), size)
            labels[i] = label
            if split == TRAIN:
                mean_acc += images[i].reshape(-1, 3).mean(axis=0) / 255.0
                mean_n += 1
            if verbose and (i + 1) % 1000 == 0:
                print(f"{split}: {i + 1}/{len(entries)}")
        images.flush()
        del images
        np.save(os.path.join(out_dir, f"{split}_labels.npy"), labels)
        counts[split] = len(entries)
    if not counts:
        raise FileNotFoundError(
            f"no {'/'.join(SPLITS)}/<class>/<image> files under {src_dir}"
        )
    with open(os.path.join(out_dir, CLASSES_FILE), "w") as f:
        json.dump(classes, f)
    mean_rgb = (mean_acc / max(mean_n, 1)).tolist() if mean_n else [0.5] * 3
    with open(os.path.join(out_dir, MEAN_FILE), "w") as f:
        json.dump(mean_rgb, f)
    return counts


class ImageNetLoader(PoolShardedMixin, Loader):
    """Packed-u8 image loader with reference augmentation semantics.

    ``data_dir`` holds the ``pack_image_dir`` output (or pass a raw image
    directory — it is packed into ``data_dir/.packed<size>`` on first use).
    Train minibatches are random ``crop_size`` crops with random horizontal
    flips; valid/test use the center crop.  Minibatch data stays uint8; the
    u8->f32 conversion and channel-mean subtraction run on-device
    (:meth:`device_preproc`).
    """

    def __init__(
        self,
        data_dir: str,
        *,
        crop_size: int = 227,
        pack_size: int = 256,
        random_flip: bool = True,
        mean_rgb: Optional[Tuple[float, float, float]] = None,
        mmap: bool = True,
        device_resident: bool = False,
        pool_sharded: bool = False,
        **kwargs,
    ):
        super().__init__(**kwargs)
        # device_resident: the PACKED u8 pool ships to HBM once
        # (device_context); per batch only [B, 4] int32 (index, oy, ox,
        # flip) crosses host->device and the random crop + flip + normalize
        # run inside the jitted step.  The TPU-first answer to a slow
        # host->device link for datasets that fit on-chip: steady-state
        # transfer drops from O(B * crop^2 * 3) bytes to O(B) — and the
        # tiny per-batch payloads enable the scanned epoch dispatch.
        self._device_resident = bool(device_resident)
        self.epoch_scan_friendly = self._device_resident
        # pool_sharded: shard the packed pool over the mesh's DATA axis —
        # REAL ImageNet (~150 GB packed at 256^2) can never fit one chip's
        # HBM; sharding multiplies capacity by the mesh size
        # (loader/pool_sharded.py has the full contract)
        if pool_sharded and not device_resident:
            raise ValueError("pool_sharded=True requires device_resident")
        self.wants_data_shards = pool_sharded
        self._mesh = None
        self._pool_offsets: Dict[str, int] = {}  # set after images load
        if not os.path.isdir(data_dir):
            raise FileNotFoundError(f"no such data_dir: {data_dir}")
        if not os.path.exists(os.path.join(data_dir, f"{TRAIN}_images.npy")):
            packed = os.path.join(data_dir, f".packed{pack_size}")
            if not os.path.exists(os.path.join(packed, f"{TRAIN}_images.npy")):
                pack_image_dir(data_dir, packed, size=pack_size)
            data_dir = packed
        self.data_dir = data_dir
        self.crop_size = int(crop_size)
        self.random_flip = random_flip
        self.images: Dict[str, np.ndarray] = {}
        self.labels: Dict[str, np.ndarray] = {}
        for split in SPLITS:
            ipath = os.path.join(data_dir, f"{split}_images.npy")
            if not os.path.exists(ipath):
                continue
            self.images[split] = np.load(
                ipath, mmap_mode="r" if mmap else None
            )
            self.labels[split] = np.load(
                os.path.join(data_dir, f"{split}_labels.npy")
            )
        if TRAIN not in self.images:
            raise FileNotFoundError(f"no train_images.npy under {data_dir}")
        h = self.images[TRAIN].shape[1]
        if self.crop_size > h:
            raise ValueError(
                f"crop_size {crop_size} exceeds packed image size {h}"
            )
        cpath = os.path.join(data_dir, CLASSES_FILE)
        self.classes = (
            json.load(open(cpath)) if os.path.exists(cpath) else None
        )
        if mean_rgb is None:
            mpath = os.path.join(data_dir, MEAN_FILE)
            mean_rgb = (
                tuple(json.load(open(mpath)))
                if os.path.exists(mpath)
                else (0.5, 0.5, 0.5)
            )
        self.mean_rgb = np.asarray(mean_rgb, np.float32)
        # offsets/concatenation ordering lives in ONE place: loader.base
        self._pool_offsets = base_pool_offsets(self.images)

    # -- Loader interface --------------------------------------------------
    @property
    def class_lengths(self) -> Dict[str, int]:
        return {k: len(v) for k, v in self.images.items()}

    @property
    def sample_shape(self) -> tuple:
        return (self.crop_size, self.crop_size, 3)

    def split_labels(self, split: str):
        return self.labels.get(split)

    def n_classes(self) -> int:
        return (
            len(self.classes)
            if self.classes is not None
            else int(self.labels[TRAIN].max()) + 1
        )

    def _crop_params(self, indices: np.ndarray, split: str):
        imgs = self.images[split]
        _, h, w, _ = imgs.shape
        cs = self.crop_size
        b = len(indices)
        if split == TRAIN:
            gen = prng.get(self.rand_name)
            oy = gen.integers(0, h - cs + 1, (b,)).astype(np.int64)
            ox = gen.integers(0, w - cs + 1, (b,)).astype(np.int64)
            flip = (
                gen.integers(0, 2, (b,)).astype(np.uint8)
                if self.random_flip
                else np.zeros(b, np.uint8)
            )
        else:
            oy = np.full(b, (h - cs) // 2, np.int64)
            ox = np.full(b, (w - cs) // 2, np.int64)
            flip = np.zeros(b, np.uint8)
        return oy, ox, flip

    def fill(self, indices: np.ndarray, split: str) -> Minibatch:
        oy, ox, flip = self._crop_params(indices, split)
        if self._device_resident:
            # [B, 4] int32 payload: pool row + crop offsets + flip bit —
            # the whole host->device transfer for this minibatch
            # (pool-sharded: the row is a LOCAL address into the owning
            # device's block)
            row = (
                self._local_addr(indices, split).astype(np.int64)
                if self.data_shards > 1
                else np.asarray(indices, np.int64)
                + self._pool_offsets[split]
            )
            data = np.stack(
                [row, oy, ox, flip.astype(np.int64)], axis=1
            ).astype(np.int32)
        else:
            from znicz_tpu.loader import native

            cs = self.crop_size
            data = native.crop_gather_u8(
                self.images[split], indices, oy, ox, flip, cs, cs
            )
        return Minibatch(
            data=data,
            labels=self.labels[split][indices],
            targets=None,
            mask=None,
            indices=indices,
        )

    def _pool_split_arrays(self):
        return self.images

    def device_context(self):
        if not self._device_resident:
            return None
        if self.wants_data_shards:
            # only this process's shards' rows materialize from the mmap
            return {"pool": self._local_pool()}
        # one up-front transfer of the packed pool; base.pool_concat uses
        # the same ordering _pool_offsets was built from
        return {"pool": base_pool_concat(self.images)}

    def device_preproc(self):
        """u8 -> f32 in [-mean, 1-mean]: runs inside the jitted step.

        device_resident: the step receives [B, 4] (row, oy, ox, flip),
        gathers the packed rows from the HBM pool and crops/flips them
        with per-sample dynamic slices — augmentation at memory speed,
        fused into the XLA program."""
        import jax
        import jax.numpy as jnp

        mean = tuple(float(m) for m in self.mean_rgb)

        if not self._device_resident:

            def pre(x, ctx):
                return x.astype(jnp.float32) * (1.0 / 255.0) - jnp.asarray(
                    mean, jnp.float32
                )

            return pre

        cs = self.crop_size

        def crop_batch(payload, pool):
            # slice each crop STRAIGHT out of the pool (one batched
            # dynamic_slice, no [B, H, W, 3] full-row intermediate):
            # measured 8.9 -> 7.6 ms/step at B=1024 on v5e vs the
            # gather-rows-then-crop form.  Flip stays the where+reverse
            # select — every index-vector-gather reformulation measured
            # 3x SLOWER (BASELINE.md round-5 crop-path table).
            def crop_one(row, y, x, f):
                c = jax.lax.dynamic_slice(
                    pool, (row, y, x, 0), (1, cs, cs, 3)
                )[0]
                return jnp.where(f > 0, c[:, ::-1], c)

            crops = jax.vmap(crop_one)(
                payload[:, 0], payload[:, 1], payload[:, 2], payload[:, 3]
            )
            return crops.astype(jnp.float32) * (1.0 / 255.0) - jnp.asarray(
                mean, jnp.float32
            )

        if self.wants_data_shards:
            # payload rows and pool rows are both device-local: the whole
            # gather+crop+normalize runs per-shard inside a shard_map
            return self._shard_map_pre(crop_batch)

        def pre(payload, ctx):
            return crop_batch(payload, ctx["pool"])

        return pre
