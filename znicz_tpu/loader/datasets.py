"""Dataset constructors for the sample zoo.

The reference ships per-sample loaders (MNIST IDX parsing under
``znicz/samples/MNIST``, CIFAR pickle loader, UCI Wine, ImageNet pipeline)
[SURVEY.md 2.3 "Znicz loaders", "Samples"].  This module reads the same
standard on-disk formats when a data directory is supplied, and otherwise
generates *deterministic synthetic stand-ins* with the same shapes/splits so
every workflow and functional test runs hermetically (this machine has no
network egress and no cached datasets).
"""

from __future__ import annotations

import gzip
import os
import pickle
from typing import Dict, Optional, Tuple

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.loader.fullbatch import FullBatchLoader


# ---------------------------------------------------------------------------
# synthetic class-conditional generator (shared)
# ---------------------------------------------------------------------------

def _synthetic_classes(
    n: int,
    shape: Tuple[int, ...],
    n_classes: int,
    *,
    rand_name: str = "datasets",
    sep: float = 2.5,
) -> Tuple[np.ndarray, np.ndarray]:
    """Gaussian blobs around per-class prototype patterns — linearly hard,
    MLP/conv easy, so seeded convergence tests behave like tiny MNIST."""
    gen = prng.get(rand_name)
    dim = int(np.prod(shape))
    protos = gen.normal((n_classes, dim), 0.0, 1.0)
    labels = gen.integers(0, n_classes, (n,)).astype(np.int32)
    x = gen.normal((n, dim), 0.0, 1.0) + sep * protos[labels]
    return x.reshape((n,) + shape).astype(np.float32), labels


def _synthetic_split(
    n_train: int,
    n_test: int,
    shape: Tuple[int, ...],
    n_classes: int,
    *,
    test_split: str = "test",
    sep: float = 2.5,
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """One prototype draw shared by both splits (train and test must be the
    SAME task), empty splits omitted."""
    x, y = _synthetic_classes(n_train + n_test, shape, n_classes, sep=sep)
    data, labels = {}, {}
    if n_train:
        data["train"], labels["train"] = x[:n_train], y[:n_train]
    if n_test:
        data[test_split], labels[test_split] = x[n_train:], y[n_train:]
    return data, labels


# ---------------------------------------------------------------------------
# MNIST
# ---------------------------------------------------------------------------

def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = int.from_bytes(f.read(4), "big")
        ndim = magic & 0xFF
        dims = [int.from_bytes(f.read(4), "big") for _ in range(ndim)]
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def mnist(
    data_dir: Optional[str] = None,
    *,
    minibatch_size: int = 100,
    validation_ratio: float = 0.0,
    flat: bool = True,
    n_train: int = 2000,
    n_test: int = 500,
    **loader_kwargs,
) -> FullBatchLoader:
    """MNIST loader: real IDX files from ``data_dir`` if present, else
    synthetic 28x28/10-class stand-in sized (n_train, n_test)."""
    data: Dict[str, np.ndarray] = {}
    labels: Dict[str, np.ndarray] = {}
    if data_dir:
        names = {
            "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
            "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
        }
        for split, (ims, labs) in names.items():
            for suffix in ("", ".gz"):
                ip = os.path.join(data_dir, ims + suffix)
                lp = os.path.join(data_dir, labs + suffix)
                if os.path.exists(ip) and os.path.exists(lp):
                    # keep u8: the loader's lazy range-normalization path
                    # converts per minibatch (fused native gather)
                    data[split] = _read_idx(ip)
                    labels[split] = _read_idx(lp).astype(np.int32)
                    break
        if set(data) not in (set(), {"train", "test"}):
            raise FileNotFoundError(
                f"{data_dir} holds only the {sorted(data)} MNIST split(s); "
                "need both train-* and t10k-* IDX files (or none, for the "
                "synthetic stand-in)"
            )
        if data:
            if "normalization" in loader_kwargs:
                # caller chose a normalization in the [-0.5, 0.5] units the
                # f32 path always produced: convert eagerly; u8 storage is
                # only for the default (range) path
                data = {
                    k: v.astype(np.float32) / 255.0 - 0.5
                    for k, v in data.items()
                }
            else:
                loader_kwargs["normalization"] = "range"
                loader_kwargs["normalization_kwargs"] = {
                    "scale": 255.0, "shift": -0.5,
                }
    if not data:
        data, labels = _synthetic_split(n_train, n_test, (28, 28), 10)
    if validation_ratio > 0:
        n = len(data["train"])
        nv = int(n * validation_ratio)
        data["valid"], labels["valid"] = data["train"][:nv], labels["train"][:nv]
        data["train"], labels["train"] = data["train"][nv:], labels["train"][nv:]
    if flat:
        data = {k: v.reshape(len(v), -1) for k, v in data.items()}
    else:
        data = {k: v.reshape(len(v), 28, 28, 1) for k, v in data.items()}
    return FullBatchLoader(
        data, labels, minibatch_size=minibatch_size, **loader_kwargs
    )


# ---------------------------------------------------------------------------
# CIFAR-10
# ---------------------------------------------------------------------------

def cifar10(
    data_dir: Optional[str] = None,
    *,
    minibatch_size: int = 100,
    n_train: int = 2000,
    n_test: int = 500,
    **loader_kwargs,
) -> FullBatchLoader:
    """CIFAR-10 NHWC loader: real python-pickle batches if present, else
    synthetic 32x32x3/10-class stand-in."""
    data: Dict[str, np.ndarray] = {}
    labels: Dict[str, np.ndarray] = {}

    def _load_batches(paths):
        xs, ys = [], []
        for p in paths:
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8))
            ys.append(np.asarray(d[b"labels"], np.int32))
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        # keep u8 NHWC: lazy range-normalization converts per minibatch
        return np.ascontiguousarray(x), np.concatenate(ys)

    loaded = False
    if data_dir:
        batch_paths = [
            os.path.join(data_dir, f"data_batch_{i}") for i in range(1, 6)
        ]
        test_path = os.path.join(data_dir, "test_batch")
        if all(os.path.exists(p) for p in batch_paths + [test_path]):
            data["train"], labels["train"] = _load_batches(batch_paths)
            data["test"], labels["test"] = _load_batches([test_path])
            if "normalization" in loader_kwargs:
                # caller's normalization expects the legacy [-0.5, 0.5] units
                data = {
                    k: v.astype(np.float32) / 255.0 - 0.5
                    for k, v in data.items()
                }
            else:
                loader_kwargs["normalization"] = "range"
                loader_kwargs["normalization_kwargs"] = {
                    "scale": 255.0, "shift": -0.5,
                }
            loaded = True
    if not loaded:
        data, labels = _synthetic_split(n_train, n_test, (32, 32, 3), 10)
    return FullBatchLoader(
        data, labels, minibatch_size=minibatch_size, **loader_kwargs
    )


# ---------------------------------------------------------------------------
# Wine (UCI: 178 samples, 13 features, 3 classes)
# ---------------------------------------------------------------------------

def wine(
    data_path: Optional[str] = None,
    *,
    minibatch_size: int = 10,
    **loader_kwargs,
) -> FullBatchLoader:
    """UCI Wine: reads ``wine.data`` CSV if given, else a synthetic
    178x13/3-class stand-in with the same proportions."""
    if data_path and os.path.exists(data_path):
        raw = np.loadtxt(data_path, delimiter=",")
        labels_all = raw[:, 0].astype(np.int32) - 1
        x_all = raw[:, 1:].astype(np.float32)
    else:
        x_all, labels_all = _synthetic_classes(178, (13,), 3, sep=3.0)
    return FullBatchLoader(
        {"train": x_all},
        {"train": labels_all},
        minibatch_size=minibatch_size,
        normalization=loader_kwargs.pop("normalization", "mean_disp"),
        **loader_kwargs,
    )


# ---------------------------------------------------------------------------
# ImageNet-class synthetic (for AlexNet workflow + bench)
# ---------------------------------------------------------------------------

def imagenet_synthetic(
    *,
    image_size: int = 227,
    n_classes: int = 1000,
    n_train: int = 512,
    n_valid: int = 128,
    minibatch_size: int = 128,
    store_u8: bool = True,
    **loader_kwargs,
) -> FullBatchLoader:
    """Synthetic ImageNet-shaped data for the AlexNet workflow: the real
    pipeline (``loader/imagenet.py``) needs the dataset on disk; shapes,
    class count AND data path here match so compiled programs are identical.

    ``store_u8`` (default): quantize to uint8 and convert/normalize
    ON-DEVICE — the same u8 -> device -> fused-affine path the real packed
    ImageNet loader uses, so benchmarks measure the production pipeline.
    """
    data, labels = _synthetic_split(
        n_train,
        n_valid,
        (image_size, image_size, 3),
        n_classes,
        test_split="valid",
        sep=1.0,
    )
    if store_u8:
        # affine-map the Gaussian blobs into 0..255 (class structure is
        # affine-invariant); "range" 255/-0.5 then lands values in [-.5, .5]
        data = {
            k: np.clip((v + 5.0) * 25.5, 0, 255).astype(np.uint8)
            for k, v in data.items()
        }
        loader_kwargs.setdefault("normalization", "range")
        loader_kwargs.setdefault(
            "normalization_kwargs", {"scale": 255.0, "shift": -0.5}
        )
        loader_kwargs.setdefault("device_convert", True)
    return FullBatchLoader(
        data, labels, minibatch_size=minibatch_size, **loader_kwargs
    )
