"""Data loaders: train/valid/test minibatch bookkeeping.

Capability parity with ``veles/loader/`` (``Loader``, ``FullBatchLoader``) and
``znicz/loader/`` [SURVEY.md 2.1 "Data loader base", 2.3 "Znicz loaders"].
TPU-native contract: every minibatch has the SAME static shape (padded to
``max_minibatch_size``) plus a validity ``mask`` — variable last batches are
masked inside the jitted step instead of triggering recompilation
(SURVEY.md §7 "Hard parts").
"""

from znicz_tpu.loader.base import (  # noqa: F401
    TRAIN,
    VALID,
    TEST,
    Loader,
    LoaderFetchError,
    Minibatch,
)
from znicz_tpu.loader.fullbatch import FullBatchLoader  # noqa: F401
from znicz_tpu.loader.prefetch import PrefetchProducerError  # noqa: F401
from znicz_tpu.loader.image import ImageDirectoryLoader  # noqa: F401
from znicz_tpu.loader.imagenet import ImageNetLoader, pack_image_dir  # noqa: F401
from znicz_tpu.loader import datasets  # noqa: F401
from znicz_tpu.loader import normalizers  # noqa: F401
