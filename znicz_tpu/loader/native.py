"""ctypes bindings for the native batch assembler.

The data-plane hot path (per-minibatch gather + normalize) runs in
``native/batch_assembler.cc`` when the shared library is available — built
on first use with g++ — and falls back to numpy transparently otherwise
(the framework stays pure-Python-runnable, like the reference's NumpyDevice
property).

Measured on this host (CIFAR-sized dataset, batch 4096): the fused
u8-gather+normalize is ~3x faster than the numpy
``data[idx].astype(f32)/255`` chain (and keeps the dataset in u8, 4x less
host RAM); the plain f32 gather is bandwidth-bound and merely matches
numpy — it exists so callers have one code path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SOURCE = os.path.join(_REPO_ROOT, "native", "batch_assembler.cc")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    """Compile (once) and dlopen the assembler; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SOURCE):
            return None
        cache = os.environ.get(
            "ZNICZ_NATIVE_CACHE", os.path.join(_REPO_ROOT, ".native_cache")
        )
        so_path = os.path.join(cache, "libbatch_assembler.so")
        try:
            if not os.path.exists(so_path) or os.path.getmtime(
                so_path
            ) < os.path.getmtime(_SOURCE):
                os.makedirs(cache, exist_ok=True)
                subprocess.run(
                    [
                        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                        "-o", so_path, _SOURCE, "-pthread",
                    ],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(so_path)
        except (OSError, subprocess.SubprocessError) as exc:
            # falling back to the numpy path is fine for correctness but
            # is a silent multi-x batch-assembly slowdown — say why
            detail = getattr(exc, "stderr", None)
            if detail:
                detail = detail.decode(errors="replace").strip()[:200]
            logging.getLogger(__name__).warning(
                "native batch assembler unavailable (%s); using the "
                "numpy fallback%s",
                exc,
                f" — compiler said: {detail}" if detail else "",
            )
            return None
        f64p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.gather_rows_f32.argtypes = [
            f64p, ctypes.c_int64, i64p, ctypes.c_int64, f64p,
        ]
        lib.gather_rows_u8_normalize.argtypes = [
            u8p, ctypes.c_int64, i64p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, f64p,
        ]
        lib.normalize_rows_f32.argtypes = [
            f64p, ctypes.c_int64, ctypes.c_int64, f64p, f64p,
        ]
        lib.crop_gather_u8.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            i64p, i64p, i64p, u8p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, u8p,
        ]
        lib.gather_rows_u8_raw.argtypes = [
            u8p, ctypes.c_int64, i64p, ctypes.c_int64, u8p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _build_and_load() is not None


def _check_indices(indices: np.ndarray, n: int) -> np.ndarray:
    """The C side does raw pointer math: reject what numpy would reject
    (and the negatives numpy would wrap) BEFORE crossing the ABI."""
    idx = np.ascontiguousarray(indices, np.int64)
    if idx.size and (idx.min() < 0 or idx.max() >= n):
        raise IndexError(
            f"indices out of range [0, {n}): min={idx.min()} max={idx.max()}"
        )
    return idx


def gather_rows(data: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """out[i] = data[indices[i]] — native parallel gather with numpy
    fallback.  ``data``: [n, ...] float32 C-contiguous."""
    lib = _build_and_load()
    flat = data.reshape(len(data), -1)
    idx = _check_indices(indices, len(data))  # both paths: no numpy wrap
    if (
        lib is None
        or flat.dtype != np.float32
        or not flat.flags["C_CONTIGUOUS"]
    ):
        return data[idx]
    out = np.empty((len(idx), flat.shape[1]), np.float32)
    lib.gather_rows_f32(flat, flat.shape[1], idx, len(idx), out)
    return out.reshape((len(idx),) + data.shape[1:])


def gather_rows_u8_raw(data: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Plain u8 row gather (no conversion) — feeds the u8->device path
    where the affine normalize runs on-device inside the XLA step."""
    lib = _build_and_load()
    flat = data.reshape(len(data), -1)
    idx = _check_indices(indices, len(data))
    if (
        lib is None
        or flat.dtype != np.uint8
        or not flat.flags["C_CONTIGUOUS"]
    ):
        return data[idx]
    out = np.empty((len(idx), flat.shape[1]), np.uint8)
    lib.gather_rows_u8_raw(flat, flat.shape[1], idx, len(idx), out)
    return out.reshape((len(idx),) + data.shape[1:])


def crop_gather_u8(
    data: np.ndarray,
    indices: np.ndarray,
    oy: np.ndarray,
    ox: np.ndarray,
    flip: np.ndarray,
    out_h: int,
    out_w: int,
) -> np.ndarray:
    """Fused gather + crop + optional horizontal flip from packed u8 images.

    ``data``: [N, H, W, C] u8; per sample i the window at (oy[i], ox[i]) of
    size (out_h, out_w) is copied (W-reversed when flip[i]).  Output stays u8;
    normalization happens on-device.  Numpy fallback when the native library
    is unavailable or ``data`` is non-contiguous/mmap-backed-but-fancy.
    """
    n, h, w, c = data.shape
    idx = _check_indices(indices, n)
    oy = np.ascontiguousarray(oy, np.int64)
    ox = np.ascontiguousarray(ox, np.int64)
    if oy.min(initial=0) < 0 or ox.min(initial=0) < 0 or (
        idx.size
        and (oy.max(initial=0) > h - out_h or ox.max(initial=0) > w - out_w)
    ):
        raise IndexError("crop window out of image bounds")
    flip_u8 = np.ascontiguousarray(flip, np.uint8)
    lib = _build_and_load()
    # np.memmap works here too: the C side reads through page faults, which
    # is exactly how a larger-than-RAM packed dataset streams from disk
    if (
        lib is not None
        and data.dtype == np.uint8
        and data.flags["C_CONTIGUOUS"]
    ):
        out = np.empty((len(idx), out_h, out_w, c), np.uint8)
        lib.crop_gather_u8(
            data.reshape(-1), h, w, c, idx, oy, ox, flip_u8, len(idx),
            out_h, out_w, out.reshape(-1),
        )
        return out
    out = np.empty((len(idx), out_h, out_w, c), data.dtype)
    for i, j in enumerate(idx):
        win = data[j, oy[i] : oy[i] + out_h, ox[i] : ox[i] + out_w]
        out[i] = win[:, ::-1] if flip_u8[i] else win
    return out


def gather_rows_u8(
    data: np.ndarray,
    indices: np.ndarray,
    *,
    scale: float = 255.0,
    shift: float = 0.0,
) -> np.ndarray:
    """Gather + u8->f32 affine normalize in one native pass."""
    lib = _build_and_load()
    flat = data.reshape(len(data), -1)
    idx = _check_indices(indices, len(data))  # both paths: no numpy wrap
    if (
        lib is None
        or flat.dtype != np.uint8
        or not flat.flags["C_CONTIGUOUS"]
    ):
        return (
            data[idx].astype(np.float32) / scale + shift
        )
    out = np.empty((len(idx), flat.shape[1]), np.float32)
    lib.gather_rows_u8_normalize(
        flat, flat.shape[1], idx, len(idx), scale, shift, out
    )
    return out.reshape((len(idx),) + data.shape[1:])
