"""Background-thread minibatch prefetching.

The reference hid loader latency behind its event-driven thread pool (the
loader unit ran concurrently with device units, SURVEY.md 1 L4); the rebuilt
hot loop is a single host thread, so decode/gather work (image files, u8
conversion) would serialize with device dispatch.  ``prefetch`` runs the
loader's generator in a worker thread with a small bounded queue — identical
yield order and PRNG draw sequence, overlapped with compute.

The producer is stage-instrumented (docs/OBSERVABILITY.md "Training
observability"): each item's **fetch** (materializing one batch from the
upstream iterable), optional **host transform** (a ``transform`` callable
run on the producer thread — decode/augment, or the workflow's device
placement) and **enqueue** (blocked handing the batch over) observe into
``znicz_pipeline_stage_seconds{stage}`` and emit matching tracer spans, so
"producer slow" (long fetch/transform) and "producer starved" (long
enqueue — the consumer is the bottleneck and the queue stayed full,
counted by ``znicz_prefetch_queue_full_total``) are distinguishable in
one capture.  The ``loader.fetch`` fault point fires inside the timed
fetch, making a slow producer a deterministic CI fixture.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional, TypeVar

from znicz_tpu import observability
from znicz_tpu.observability import pipeline as _pipeline
from znicz_tpu.utils import faults

T = TypeVar("T")

_SENTINEL = object()


class PrefetchProducerError(RuntimeError):
    """The prefetch producer thread died without delivering its
    end-of-epoch sentinel (or the error that killed it) — the typed,
    consumer-visible form of a dead producer.  Ordinary producer
    exceptions re-raise AS THEMSELVES at the consumer's next pull; this
    only fires when the thread is gone and nothing explains why (e.g.
    it never started), turning what used to be an unbounded ``q.get()``
    hang into a diagnosis (the ZNC013 "a thread death must be a typed
    event" contract)."""


def prefetch(
    iterable: Iterable[T],
    depth: int = 2,
    *,
    transform: Optional[Callable[[T], T]] = None,
    transform_stage: Optional[str] = _pipeline.STAGE_TRANSFORM,
) -> Iterator[T]:
    """Yield from ``iterable``, produced ``depth`` items ahead in a thread.

    ``transform`` (optional) is applied to each item ON the producer
    thread — host decode/augment work, or the workflow's device-placement
    closure — timed as the ``transform_stage`` pipeline stage (pass
    ``transform_stage=None`` when the callable owns its own
    instrumentation, e.g. an :class:`~znicz_tpu.observability.H2DProbe`).

    Exceptions in the producer (fetch or transform) re-raise at the
    consumer's next pull.  If the consumer abandons the iterator
    (exception mid-epoch, interrupt), closing the generator signals the
    worker to stop — no thread or queued batches leak.
    """
    q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
    stop = threading.Event()
    error: list = []

    # per-stage producer telemetry: each span is on the LOADER's own
    # thread track in Perfetto, so producer stalls line up against the
    # consumer's znicz_prefetch_wait_seconds histogram and the
    # train/serve spans they starve.  No-op span cost when the tracer
    # is idle; one histogram observe per stage per item otherwise.
    stage_hist = _pipeline.stage_seconds()
    queue_full = observability.counter(
        _pipeline.QUEUE_FULL_METRIC,
        "items whose producer-side enqueue found the prefetch queue "
        "full at least once (depth exhaustion: the consumer, not the "
        "producer, is behind)",
    )

    def worker():
        tracer = observability.get_tracer()
        try:
            it = iter(iterable)
            while True:
                t0 = time.perf_counter()
                with tracer.span("loader/fetch"):
                    # the fault fires INSIDE the timed window, so an
                    # injected delay reads as a slow producer to the
                    # attribution (the input-bound CI fixture)
                    faults.fire("loader.fetch")
                    item = next(it, _SENTINEL)
                stage_hist.labels(stage=_pipeline.STAGE_FETCH).observe(
                    time.perf_counter() - t0
                )
                if item is _SENTINEL:
                    break
                if transform is not None:
                    if transform_stage is None:
                        item = transform(item)
                    else:
                        t0 = time.perf_counter()
                        with tracer.span(f"loader/{transform_stage}"):
                            item = transform(item)
                        stage_hist.labels(stage=transform_stage).observe(
                            time.perf_counter() - t0
                        )
                # bounded put that gives up when the consumer went away
                t0 = time.perf_counter()
                try:
                    # non-blocking first attempt: ANY fullness counts as
                    # a depth-exhaustion stall, even one shorter than
                    # the polling timeout below
                    q.put_nowait(item)
                except queue.Full:  # znicz-check: disable=ZNC008
                    queue_full.inc()
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        # polling control flow, not a swallowed failure
                        except queue.Full:  # znicz-check: disable=ZNC008
                            continue
                stage_hist.labels(stage=_pipeline.STAGE_ENQUEUE).observe(
                    time.perf_counter() - t0
                )
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — must cross threads
            error.append(e)
        finally:
            # deliver the sentinel with the same give-up-on-stop loop as
            # items: a fixed timeout would lose it when the consumer stalls
            # longer (e.g. first-step XLA compile) and deadlock the epoch
            while not stop.is_set():
                try:
                    q.put(_SENTINEL, timeout=0.1)
                    break
                # polling control flow, not a swallowed failure
                except queue.Full:  # znicz-check: disable=ZNC008
                    continue

    # how long the training loop blocked waiting on the loader: the
    # "is the input pipeline the bottleneck" histogram — near-zero waits
    # mean the device is the limit; long waits mean the loader is
    wait = observability.histogram(
        "znicz_prefetch_wait_seconds",
        "seconds the consumer blocked waiting for the next minibatch",
    )
    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            t0 = time.perf_counter()
            # bounded get with a liveness check: a producer thread that
            # died without its sentinel (hard kill, never started) must
            # become a typed error, not an unbounded q.get() hang
            while True:
                try:
                    item = q.get(timeout=0.5)
                    break
                except queue.Empty:  # znicz-check: disable=ZNC008
                    if not t.is_alive() and q.empty():
                        if error:
                            raise error[0]
                        raise PrefetchProducerError(
                            "prefetch producer thread died without "
                            "delivering a sentinel or an error"
                        )
            wait.observe(time.perf_counter() - t0)
            if item is _SENTINEL:
                if error:
                    raise error[0]
                return
            yield item
    finally:
        # runs on normal exhaustion AND on generator close/abandonment
        stop.set()
        while True:  # unblock a worker stuck in put()
            try:
                q.get_nowait()
            # drain-until-empty control flow, not a swallowed failure
            except queue.Empty:  # znicz-check: disable=ZNC008
                break
