"""Background-thread minibatch prefetching.

The reference hid loader latency behind its event-driven thread pool (the
loader unit ran concurrently with device units, SURVEY.md 1 L4); the rebuilt
hot loop is a single host thread, so decode/gather work (image files, u8
conversion) would serialize with device dispatch.  ``prefetch`` runs the
loader's generator in a worker thread with a small bounded queue — identical
yield order and PRNG draw sequence, overlapped with compute.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterable, Iterator, TypeVar

from znicz_tpu import observability

T = TypeVar("T")

_SENTINEL = object()


def prefetch(iterable: Iterable[T], depth: int = 2) -> Iterator[T]:
    """Yield from ``iterable``, produced ``depth`` items ahead in a thread.

    Exceptions in the producer re-raise at the consumer's next pull.  If the
    consumer abandons the iterator (exception mid-epoch, interrupt), closing
    the generator signals the worker to stop — no thread or queued batches
    leak.
    """
    q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
    stop = threading.Event()
    error: list = []

    def worker():
        # producer-side spans (ROADMAP observability next-rung): each
        # span is the time the LOADER spent materializing one batch —
        # on its own thread track in Perfetto, so loader stalls line up
        # against the consumer's znicz_prefetch_wait_seconds histogram
        # and the train/serve spans they starve.  No-op cost when the
        # tracer is idle.
        tracer = observability.get_tracer()
        try:
            it = iter(iterable)
            while True:
                with tracer.span("loader/prefetch_produce"):
                    item = next(it, _SENTINEL)
                if item is _SENTINEL:
                    break
                # bounded put that gives up when the consumer went away
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    # polling control flow, not a swallowed failure
                    except queue.Full:  # znicz-check: disable=ZNC008
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — must cross threads
            error.append(e)
        finally:
            # deliver the sentinel with the same give-up-on-stop loop as
            # items: a fixed timeout would lose it when the consumer stalls
            # longer (e.g. first-step XLA compile) and deadlock the epoch
            while not stop.is_set():
                try:
                    q.put(_SENTINEL, timeout=0.1)
                    break
                # polling control flow, not a swallowed failure
                except queue.Full:  # znicz-check: disable=ZNC008
                    continue

    # how long the training loop blocked waiting on the loader: the
    # "is the input pipeline the bottleneck" histogram — near-zero waits
    # mean the device is the limit; long waits mean the loader is
    wait = observability.histogram(
        "znicz_prefetch_wait_seconds",
        "seconds the consumer blocked waiting for the next minibatch",
    )
    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            t0 = time.perf_counter()
            item = q.get()
            wait.observe(time.perf_counter() - t0)
            if item is _SENTINEL:
                if error:
                    raise error[0]
                return
            yield item
    finally:
        # runs on normal exhaustion AND on generator close/abandonment
        stop.set()
        while True:  # unblock a worker stuck in put()
            try:
                q.get_nowait()
            # drain-until-empty control flow, not a swallowed failure
            except queue.Empty:  # znicz-check: disable=ZNC008
                break
