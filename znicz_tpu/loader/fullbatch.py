"""In-memory full-batch loader.

Capability parity with ``veles/loader/fullbatch.py`` ``FullBatchLoader``
[SURVEY.md 2.1]: the whole dataset lives in host arrays; minibatches are
gathered by index.  Also covers the reference's targets path
(``FullBatchLoaderMSE``-style: regression/autoencoder targets instead of int
labels).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from znicz_tpu.loader import normalizers
from znicz_tpu.loader.base import (
    SPLITS,
    Loader,
    Minibatch,
    pool_concat,
    pool_offsets,
)


class FullBatchLoader(Loader):
    """Serve minibatches from per-split in-memory arrays.

    ``data[split]``: [n, ...] float array; ``labels[split]``: [n] ints or
    None; ``targets[split]``: same-shape-as-needed float array or None.
    """

    def __init__(
        self,
        data: Dict[str, np.ndarray],
        labels: Optional[Dict[str, np.ndarray]] = None,
        targets: Optional[Dict[str, np.ndarray]] = None,
        *,
        normalization: str = "none",
        normalization_kwargs: Optional[dict] = None,
        device_convert: bool = False,
        device_resident: bool = False,
        pool_sharded: bool = False,
        **kwargs,
    ):
        super().__init__(**kwargs)
        # zero-length splits are simply absent (reshape/normalize of empty
        # arrays has no meaning and callers build sizes from configs)
        self.data = {
            k: np.asarray(v)
            for k, v in data.items()
            if v is not None and len(v)
        }
        if not self.data:
            raise ValueError(
                "FullBatchLoader needs at least one non-empty split"
            )
        self.labels = {
            k: np.asarray(v, np.int32)
            for k, v in (labels or {}).items()
            if v is not None
        }
        self.targets = {
            k: np.asarray(v) for k, v in (targets or {}).items() if v is not None
        }
        for split in self.data:
            if split not in SPLITS:
                raise ValueError(f"unknown split {split!r}")
        train = self.data.get("train")
        if train is None and normalization in ("linear", "mean_disp"):
            raise ValueError(
                f"normalization={normalization!r} must be fitted on a "
                "'train' split, but this loader has none"
            )
        fit_src = train if train is not None else np.zeros((1, 1))
        self.normalizer = normalizers.fit(
            normalization,
            fit_src.reshape(len(fit_src), -1),
            **(normalization_kwargs or {}),
        )
        # uint8 data + "range" normalization stays u8 (4x less host RAM):
        # the affine convert fuses into the per-minibatch native gather.
        self._lazy_u8 = all(
            raw.dtype == np.uint8 for raw in self.data.values()
        ) and self.normalizer["kind"] == "range"
        # device_convert: go further — ship the MINIBATCH as u8 too (4x less
        # host->device transfer) and run the affine on-device, fused into the
        # jitted step (see Loader.device_preproc).
        self._device_convert = device_convert and self._lazy_u8
        # device_resident: the whole dataset lives in device HBM (one
        # up-front transfer); per batch only the int32 INDEX VECTOR crosses
        # host->device, and the jitted step gathers + normalizes in HBM.
        # The TPU-first mode for datasets that fit on-chip — per-step input
        # transfer drops from O(batch x sample) to O(batch) bytes.
        self._device_resident = device_resident
        # per-batch host payloads are bare index vectors: stacking a whole
        # epoch of them is bytes, so the workflow may compile each split as
        # ONE lax.scan dispatch (Workflow._use_epoch_scan)
        self.epoch_scan_friendly = device_resident
        self._pool_offsets: Dict[str, int] = (
            pool_offsets(self.data) if device_resident else {}
        )
        # pool_sharded: the HBM pool shards over the mesh's DATA axis —
        # each device holds 1/D of every split, so dataset capacity is
        # D x one chip's free HBM instead of one chip's (max rows ~=
        # n_data * HBM_free / bytes_per_sample).  Locality is by
        # construction: sampling is per-shard (batch position block s only
        # draws from shard s's rows — see set_data_shards), payloads are
        # LOCAL pool addresses, and the gather runs inside a shard_map, so
        # no collective ever touches pool-sized data.  Epoch semantics:
        # every sample still appears exactly once per epoch; minibatch
        # COMPOSITION differs from the global shuffle (each 1/D batch
        # block mixes only within its shard).
        if pool_sharded and not device_resident:
            raise ValueError("pool_sharded=True requires device_resident")
        if pool_sharded and self.balanced:
            raise ValueError(
                "pool_sharded is incompatible with balanced=True (the "
                "class-balanced shuffle is a global permutation; per-shard "
                "sampling owns the batch layout)"
            )
        self._pool_sharded = pool_sharded
        self.wants_data_shards = pool_sharded
        self.data_shards = 1
        self._mesh = None
        self._local_split_offset: Dict[str, int] = {}
        if not self._lazy_u8:
            # Normalize each immutable split ONCE here, not per minibatch.
            self.data = {
                split: normalizers.apply(
                    self.normalizer,
                    raw.reshape(len(raw), -1).astype(np.float32),
                ).reshape(raw.shape)
                for split, raw in self.data.items()
            }

    # -- data-axis pool sharding -------------------------------------------
    def set_data_shards(self, n: int) -> None:
        """Partition every split into ``n`` equal row blocks (shard s of a
        split owns rows [s*len/n, (s+1)*len/n)); sampling becomes
        per-shard so batch position block s only references shard s."""
        bs = self.max_minibatch_size
        if bs % n:
            raise ValueError(
                f"pool_sharded: minibatch_size {bs} not divisible by the "
                f"data axis {n}"
            )
        for split, arr in self.data.items():
            if len(arr) % bs:
                raise ValueError(
                    f"pool_sharded: split {split!r} has {len(arr)} rows, "
                    f"not a multiple of minibatch_size {bs} (static equal "
                    "per-shard chunks need full batches; pad or trim the "
                    "split)"
                )
        self.data_shards = int(n)
        self._order.clear()  # orders must be rebuilt in blocked layout
        # per-device block layout = the SHARED pool ordering contract
        # applied to one shard's chunk of each split
        self._local_split_offset = pool_offsets(
            {s: arr[: len(arr) // n] for s, arr in self.data.items()}
        )

    def _blocked_order(self, per_shard_rows) -> np.ndarray:
        """[D, c] per-shard row ids -> epoch order where batch b's position
        block s holds shard s's rows [b*B/D, (b+1)*B/D)."""
        d, c = per_shard_rows.shape
        rows_per = self.max_minibatch_size // d
        steps = c // rows_per
        return (
            per_shard_rows.reshape(d, steps, rows_per)
            .transpose(1, 0, 2)
            .reshape(-1)
        )

    def _split_order(self, split: str) -> np.ndarray:
        if self.data_shards <= 1:
            return super()._split_order(split)
        n = self.class_lengths[split]
        order = self._order.get(split)
        if order is None or len(order) != n:
            c = n // self.data_shards
            order = self._blocked_order(
                np.arange(n).reshape(self.data_shards, c)
            )
            self._order[split] = order
        return order

    def reshuffle(self, split: str = "train") -> None:
        if self.data_shards <= 1:
            return super().reshuffle(split)
        n = self.class_lengths.get(split, 0)
        if not n:
            return
        from znicz_tpu.core import prng

        gen = prng.get(self.rand_name)
        c = n // self.data_shards
        per_shard = np.stack(
            [s * c + gen.permutation(c) for s in range(self.data_shards)]
        )
        self._order[split] = self._blocked_order(per_shard)

    def _validate_batch_indices(self, idx: np.ndarray, split: str) -> None:
        if self.data_shards <= 1:
            return
        c = self.class_lengths[split] // self.data_shards
        rows_per = len(idx) // self.data_shards
        expected = np.repeat(np.arange(self.data_shards), rows_per)
        if not np.array_equal(idx // c, expected):
            raise AssertionError(
                "pool_sharded alignment violated: batch position block s "
                "must only reference data-axis shard s (a local gather "
                "would silently fetch wrong rows)"
            )

    def place_device_context(self, parallel):
        if not self._pool_sharded:
            return super().place_device_context(parallel)
        if parallel is None:
            raise ValueError(
                "pool_sharded=True needs parallel=DataParallel(mesh)"
            )
        if self.data_shards != parallel.n_data:
            raise ValueError(
                f"pool_sharded: set_data_shards({parallel.n_data}) was not "
                f"applied (have {self.data_shards}); initialize the "
                "workflow instead of placing the context by hand"
            )
        self._mesh = parallel.mesh
        # shard the pool rows over the data axis: this process ships ONLY
        # its shards' rows; shard_batch assembles the global array
        # (make_array_from_process_local_data on multi-host)
        return {"pool": parallel.shard_batch(self._local_pool())}

    def _local_pool(self) -> np.ndarray:
        """Shard-major pool rows owned by THIS process: for each of its
        data-axis shards, each split's chunk in the shared pool order."""
        d = self.data_shards
        lo = self.process_index * d // self.process_count
        hi = (self.process_index + 1) * d // self.process_count
        blocks = [
            pool_concat(
                {
                    split: arr[len(arr) // d * s: len(arr) // d * (s + 1)]
                    for split, arr in self.data.items()
                }
            )
            for s in range(lo, hi)
        ]
        return np.concatenate(blocks)

    def device_context(self):
        if not self._device_resident:
            return None
        if self._pool_sharded:
            return {"pool": self._local_pool()}
        # Built fresh per call (once per initialize) and NOT retained: the
        # workflow device_puts it, so keeping a concatenated host copy next
        # to self.data would double host RAM for exactly the datasets this
        # mode targets.  (np.concatenate still peaks at 2x transiently.)
        # base.pool_concat uses the same ordering _pool_offsets came from.
        return {"pool": pool_concat(self.data)}

    def device_preproc(self):
        import jax.numpy as jnp

        if self._device_resident:
            if self._lazy_u8:
                scale = self.normalizer["scale"]
                shift = self.normalizer["shift"]

                def convert(x):
                    return x.astype(jnp.float32) * (1.0 / scale) + shift

            else:  # pool already normalized f32: pure gather

                def convert(x):
                    return x

            if self._pool_sharded:
                import jax
                from jax.sharding import PartitionSpec as P

                from znicz_tpu.parallel.mesh import DATA_AXIS

                mesh = self._mesh
                spec = P(DATA_AXIS)

                def gather_local(i, p):
                    # i holds LOCAL addresses into this device's pool
                    # block (per-shard sampling guarantees locality) —
                    # the gather never leaves the device
                    return p[i]

                def pre(idx, ctx):
                    x = jax.shard_map(
                        gather_local,
                        mesh=mesh,
                        in_specs=(spec, spec),
                        out_specs=spec,
                    )(idx, ctx["pool"])
                    return convert(x)

            else:

                def pre(idx, ctx):
                    return convert(ctx["pool"][idx])

            return pre
        if not self._device_convert:
            return None
        scale, shift = self.normalizer["scale"], self.normalizer["shift"]

        def pre(x, ctx):
            return x.astype(jnp.float32) * (1.0 / scale) + shift

        return pre

    @property
    def class_lengths(self) -> Dict[str, int]:
        return {k: len(v) for k, v in self.data.items()}

    @property
    def sample_shape(self) -> tuple:
        return next(iter(self.data.values())).shape[1:]

    def split_labels(self, split: str):
        return self.labels.get(split)

    def fill(self, indices: np.ndarray, split: str) -> Minibatch:
        if self._device_resident:
            # ship only indices; the jitted step's device_preproc gathers
            # from the HBM-resident pool
            if self.data_shards > 1:
                # LOCAL address within the owning device's pool block:
                # split-chunk offset + position inside shard s's chunk
                idx = np.asarray(indices, np.int64)
                c = self.class_lengths[split] // self.data_shards
                data = (
                    self._local_split_offset[split] + idx % c
                ).astype(np.int32)
            else:
                data = (
                    np.asarray(indices, np.int32)
                    + np.int32(self._pool_offsets.get(split, 0))
                )
            labels = (
                self.labels[split][indices] if split in self.labels else None
            )
            targets = (
                self.targets[split][indices]
                if split in self.targets
                else None
            )
            return Minibatch(
                data=data, labels=labels, targets=targets, mask=None,
                indices=indices,
            )
        raw = self.data[split]
        if self._device_convert:
            from znicz_tpu.loader import native

            data = native.gather_rows_u8_raw(raw, indices)
        elif self._lazy_u8:
            # fused native gather + u8->f32 affine normalize (~3x faster
            # than the numpy chain; numpy fallback inside)
            from znicz_tpu.loader import native

            data = native.gather_rows_u8(
                raw,
                indices,
                scale=self.normalizer["scale"],
                shift=self.normalizer["shift"],
            )
        else:
            data = raw[indices]  # plain f32 gather: numpy already optimal
        labels = (
            self.labels[split][indices] if split in self.labels else None
        )
        targets = (
            self.targets[split][indices] if split in self.targets else None
        )
        return Minibatch(
            data=data, labels=labels, targets=targets, mask=None, indices=indices
        )
