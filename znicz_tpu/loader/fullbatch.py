"""In-memory full-batch loader.

Capability parity with ``veles/loader/fullbatch.py`` ``FullBatchLoader``
[SURVEY.md 2.1]: the whole dataset lives in host arrays; minibatches are
gathered by index.  Also covers the reference's targets path
(``FullBatchLoaderMSE``-style: regression/autoencoder targets instead of int
labels).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from znicz_tpu.loader import normalizers
from znicz_tpu.loader.base import (
    SPLITS,
    Loader,
    Minibatch,
    pool_concat,
    pool_offsets,
)
from znicz_tpu.loader.pool_sharded import PoolShardedMixin


class FullBatchLoader(PoolShardedMixin, Loader):
    """Serve minibatches from per-split in-memory arrays.

    ``data[split]``: [n, ...] float array; ``labels[split]``: [n] ints or
    None; ``targets[split]``: same-shape-as-needed float array or None.
    """

    def __init__(
        self,
        data: Dict[str, np.ndarray],
        labels: Optional[Dict[str, np.ndarray]] = None,
        targets: Optional[Dict[str, np.ndarray]] = None,
        *,
        normalization: str = "none",
        normalization_kwargs: Optional[dict] = None,
        device_convert: bool = False,
        device_resident: bool = False,
        pool_sharded: bool = False,
        **kwargs,
    ):
        super().__init__(**kwargs)
        # zero-length splits are simply absent (reshape/normalize of empty
        # arrays has no meaning and callers build sizes from configs)
        self.data = {
            k: np.asarray(v)
            for k, v in data.items()
            if v is not None and len(v)
        }
        if not self.data:
            raise ValueError(
                "FullBatchLoader needs at least one non-empty split"
            )
        self.labels = {
            k: np.asarray(v, np.int32)
            for k, v in (labels or {}).items()
            if v is not None
        }
        self.targets = {
            k: np.asarray(v) for k, v in (targets or {}).items() if v is not None
        }
        for split in self.data:
            if split not in SPLITS:
                raise ValueError(f"unknown split {split!r}")
        train = self.data.get("train")
        if train is None and normalization in ("linear", "mean_disp"):
            raise ValueError(
                f"normalization={normalization!r} must be fitted on a "
                "'train' split, but this loader has none"
            )
        fit_src = train if train is not None else np.zeros((1, 1))
        self.normalizer = normalizers.fit(
            normalization,
            fit_src.reshape(len(fit_src), -1),
            **(normalization_kwargs or {}),
        )
        # uint8 data + "range" normalization stays u8 (4x less host RAM):
        # the affine convert fuses into the per-minibatch native gather.
        self._lazy_u8 = all(
            raw.dtype == np.uint8 for raw in self.data.values()
        ) and self.normalizer["kind"] == "range"
        # device_convert: go further — ship the MINIBATCH as u8 too (4x less
        # host->device transfer) and run the affine on-device, fused into the
        # jitted step (see Loader.device_preproc).
        self._device_convert = device_convert and self._lazy_u8
        # device_resident: the whole dataset lives in device HBM (one
        # up-front transfer); per batch only the int32 INDEX VECTOR crosses
        # host->device, and the jitted step gathers + normalizes in HBM.
        # The TPU-first mode for datasets that fit on-chip — per-step input
        # transfer drops from O(batch x sample) to O(batch) bytes.
        self._device_resident = device_resident
        # per-batch host payloads are bare index vectors: stacking a whole
        # epoch of them is bytes, so the workflow may compile each split as
        # ONE lax.scan dispatch (Workflow._use_epoch_scan)
        self.epoch_scan_friendly = device_resident
        self._pool_offsets: Dict[str, int] = (
            pool_offsets(self.data) if device_resident else {}
        )
        # pool_sharded: shard the HBM pool over the mesh's DATA axis
        # (capacity = n_data x one chip's HBM, per-shard sampling, local
        # shard_map gathers — loader/pool_sharded.py has the full story)
        if pool_sharded and not device_resident:
            raise ValueError("pool_sharded=True requires device_resident")
        self.wants_data_shards = pool_sharded
        self._mesh = None
        if not self._lazy_u8:
            # Normalize each immutable split ONCE here, not per minibatch.
            self.data = {
                split: normalizers.apply(
                    self.normalizer,
                    raw.reshape(len(raw), -1).astype(np.float32),
                ).reshape(raw.shape)
                for split, raw in self.data.items()
            }

    # -- data-axis pool sharding (PoolShardedMixin) ------------------------
    def _pool_split_arrays(self):
        return self.data

    def device_context(self):
        if not self._device_resident:
            return None
        if self.wants_data_shards:
            return {"pool": self._local_pool()}
        # Built fresh per call (once per initialize) and NOT retained: the
        # workflow device_puts it, so keeping a concatenated host copy next
        # to self.data would double host RAM for exactly the datasets this
        # mode targets.  (np.concatenate still peaks at 2x transiently.)
        # base.pool_concat uses the same ordering _pool_offsets came from.
        return {"pool": pool_concat(self.data)}

    def device_preproc(self):
        import jax.numpy as jnp

        if self._device_resident:
            if self._lazy_u8:
                scale = self.normalizer["scale"]
                shift = self.normalizer["shift"]

                def convert(x):
                    return x.astype(jnp.float32) * (1.0 / scale) + shift

            else:  # pool already normalized f32: pure gather

                def convert(x):
                    return x

            if self.wants_data_shards:
                # i holds LOCAL addresses into this device's pool block
                # (per-shard sampling guarantees locality) — the gather
                # never leaves the device
                pre = self._shard_map_pre(lambda i, p: convert(p[i]))

            else:

                def pre(idx, ctx):
                    return convert(ctx["pool"][idx])

            return pre
        if not self._device_convert:
            return None
        scale, shift = self.normalizer["scale"], self.normalizer["shift"]

        def pre(x, ctx):
            return x.astype(jnp.float32) * (1.0 / scale) + shift

        return pre

    @property
    def class_lengths(self) -> Dict[str, int]:
        return {k: len(v) for k, v in self.data.items()}

    @property
    def sample_shape(self) -> tuple:
        return next(iter(self.data.values())).shape[1:]

    def split_labels(self, split: str):
        return self.labels.get(split)

    def fill(self, indices: np.ndarray, split: str) -> Minibatch:
        if self._device_resident:
            # ship only indices; the jitted step's device_preproc gathers
            # from the HBM-resident pool
            if self.data_shards > 1:
                data = self._local_addr(indices, split)
            else:
                data = (
                    np.asarray(indices, np.int32)
                    + np.int32(self._pool_offsets.get(split, 0))
                )
            labels = (
                self.labels[split][indices] if split in self.labels else None
            )
            targets = (
                self.targets[split][indices]
                if split in self.targets
                else None
            )
            return Minibatch(
                data=data, labels=labels, targets=targets, mask=None,
                indices=indices,
            )
        raw = self.data[split]
        if self._device_convert:
            from znicz_tpu.loader import native

            data = native.gather_rows_u8_raw(raw, indices)
        elif self._lazy_u8:
            # fused native gather + u8->f32 affine normalize (~3x faster
            # than the numpy chain; numpy fallback inside)
            from znicz_tpu.loader import native

            data = native.gather_rows_u8(
                raw,
                indices,
                scale=self.normalizer["scale"],
                shift=self.normalizer["shift"],
            )
        else:
            data = raw[indices]  # plain f32 gather: numpy already optimal
        labels = (
            self.labels[split][indices] if split in self.labels else None
        )
        targets = (
            self.targets[split][indices] if split in self.targets else None
        )
        return Minibatch(
            data=data, labels=labels, targets=targets, mask=None, indices=indices
        )
