"""Input normalizers.

Capability parity with the reference loader's normalization modes
(``veles/loader`` normalizers: linear range, mean-dispersion, external mean
image for ImageNet) [SURVEY.md 2.1 "Data loader base"].  Each normalizer is
``fit(data) -> state`` + ``apply(state, data)``; state is plain numpy so it
pickles into snapshots.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def fit(kind: str, data: np.ndarray, **kwargs) -> Dict[str, object]:
    """Compute normalizer state from training data."""
    if kind == "none":
        return {"kind": "none"}
    if kind == "linear":  # scale to [-1, 1] per-feature
        lo = data.min(axis=0)
        hi = data.max(axis=0)
        span = np.where(hi > lo, hi - lo, 1.0)
        return {"kind": "linear", "lo": lo, "span": span}
    if kind == "mean_disp":  # zero mean, unit dispersion per-feature
        mean = data.mean(axis=0)
        disp = data.std(axis=0)
        return {"kind": "mean_disp", "mean": mean, "disp": np.where(disp > 0, disp, 1.0)}
    if kind == "range":  # fixed affine x/scale + shift (e.g. /255 - 0.5)
        return {
            "kind": "range",
            "scale": float(kwargs.get("scale", 255.0)),
            "shift": float(kwargs.get("shift", 0.0)),
        }
    if kind == "external_mean":  # subtract a provided mean image (AlexNet)
        return {"kind": "external_mean", "mean": np.asarray(kwargs["mean"])}
    raise ValueError(f"unknown normalizer {kind!r}")


def apply(state: Dict[str, object], data: np.ndarray) -> np.ndarray:
    kind = state["kind"]
    if kind == "none":
        return data
    data = data.astype(np.float32)
    if kind == "linear":
        return 2.0 * (data - state["lo"]) / state["span"] - 1.0
    if kind == "mean_disp":
        return (data - state["mean"]) / state["disp"]
    if kind == "range":
        return data / state["scale"] + state["shift"]
    if kind == "external_mean":
        return data - state["mean"]
    raise ValueError(f"unknown normalizer {kind!r}")
