"""File-system image loader.

Capability parity with the reference image loaders (``veles/loader/image.py``,
``znicz/loader/`` file-system image pipelines [SURVEY.md 2.1 "Data loader
base", 2.3 "Znicz loaders"]): ingest a directory tree of image files into
train/valid/test minibatches with labels from directory names.

Layout (reference convention):
    root/train/<class_name>/*.png
    root/valid/<class_name>/*.png   (optional)
    root/test/<class_name>/*.png    (optional)

Images load lazily per minibatch (streaming — datasets larger than host
memory work), decoded with matplotlib (PNG) and resized by nearest-neighbor
to a common ``target_shape``.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from znicz_tpu.loader import normalizers
from znicz_tpu.loader.base import SPLITS, Loader, Minibatch

IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp")


def _read_image(path: str) -> np.ndarray:
    import matplotlib.image as mpimg

    raw = np.asarray(mpimg.imread(path))
    # integer-decoded formats (JPEG) are 0..255; float (PNG) already 0..1 —
    # decide by dtype, never by content, so dark images scale consistently
    scale = 255.0 if np.issubdtype(raw.dtype, np.integer) else 1.0
    img = raw.astype(np.float32) / scale
    if img.ndim == 2:
        img = img[..., None]
    if img.shape[-1] == 4:  # drop alpha
        img = img[..., :3]
    return img


def _resize_nearest(img: np.ndarray, h: int, w: int) -> np.ndarray:
    ih, iw = img.shape[:2]
    if (ih, iw) == (h, w):
        return img
    rows = (np.arange(h) * ih / h).astype(np.int64)
    cols = (np.arange(w) * iw / w).astype(np.int64)
    return img[rows][:, cols]


class ImageDirectoryLoader(Loader):
    """Serve labeled images from a directory tree, lazily.

    ``target_shape``: (H, W) or (H, W, C); channels inferred from the first
    image when omitted.  ``grayscale``: average channels to 1.
    ``normalization``: loader normalizer kind ("none", "mean_disp",
    "linear", "range"); dataset statistics are fitted once at construction
    on up to ``normalization_fit_samples`` training images (the loader is
    lazy — a full pass would defeat streaming) and applied per minibatch.
    """

    def __init__(
        self,
        root_dir: str,
        *,
        target_shape: Optional[Tuple[int, ...]] = None,
        grayscale: bool = False,
        normalization: str = "none",
        normalization_kwargs: Optional[dict] = None,
        normalization_fit_samples: int = 512,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.root_dir = root_dir
        self.grayscale = grayscale
        self.index: Dict[str, List[Tuple[str, int]]] = {}
        classes: List[str] = []
        for split in SPLITS:
            split_dir = os.path.join(root_dir, split)
            if not os.path.isdir(split_dir):
                continue
            entries: List[Tuple[str, int]] = []
            for cls in sorted(os.listdir(split_dir)):
                cls_dir = os.path.join(split_dir, cls)
                if not os.path.isdir(cls_dir):
                    continue
                files = [
                    os.path.join(cls_dir, fname)
                    for fname in sorted(os.listdir(cls_dir))
                    if fname.lower().endswith(IMAGE_EXTENSIONS)
                ]
                if not files:
                    continue  # a class only exists if it has samples
                if cls not in classes:
                    classes.append(cls)
                label = classes.index(cls)
                entries.extend((path, label) for path in files)
            if entries:
                self.index[split] = entries
        if not self.index:
            raise FileNotFoundError(
                f"no {'/'.join(SPLITS)}/<class>/*.png images under {root_dir}"
            )
        self.classes = classes
        if target_shape is None:
            first = _read_image(self.index[next(iter(self.index))][0][0])
            target_shape = first.shape[:2]  # channels decided below
        if len(target_shape) == 2:
            target_shape = tuple(target_shape) + (1 if grayscale else 3,)
        if grayscale and target_shape[-1] != 1:
            raise ValueError(
                f"grayscale=True conflicts with target_shape {target_shape}"
            )
        self.target_shape = tuple(int(s) for s in target_shape)
        if normalization in ("none", "range"):
            self.normalizer = normalizers.fit(
                normalization, np.zeros(0), **(normalization_kwargs or {})
            )
        else:
            # fit dataset statistics on a deterministic sample of the
            # training split — STRIDED across the (class-major) sorted
            # index so the sample spans classes instead of exhausting the
            # first one(s); no PRNG draw, so the reproducibility stream
            # stays untouched
            split = "train" if "train" in self.index else next(
                iter(self.index)
            )
            all_entries = self.index[split]
            n_fit = min(normalization_fit_samples, len(all_entries))
            pick = np.linspace(
                0, len(all_entries) - 1, n_fit
            ).astype(int)  # spans the whole split in every regime
            entries = [all_entries[i] for i in pick]
            h, w, c = self.target_shape
            sample = np.stack(
                [
                    self._load_one(path, h, w, c)
                    for path, _ in entries
                ]
            ).reshape(len(entries), -1)
            self.normalizer = normalizers.fit(
                normalization, sample, **(normalization_kwargs or {})
            )

    @property
    def class_lengths(self) -> Dict[str, int]:
        return {split: len(v) for split, v in self.index.items()}

    @property
    def sample_shape(self) -> tuple:
        return self.target_shape

    def split_labels(self, split: str):
        # enables balanced=True minibatch serving (Loader.reshuffle)
        return np.asarray([label for _, label in self.index[split]], np.int32)

    @staticmethod
    def _load_one(path: str, h: int, w: int, c: int) -> np.ndarray:
        img = _resize_nearest(_read_image(path), h, w)
        if img.shape[-1] != c:
            if c == 1:  # color source, gray target: average (not slice)
                img = img.mean(axis=-1, keepdims=True)
            elif img.shape[-1] == 1:  # gray source, color target
                img = np.repeat(img, c, axis=-1)
            else:
                img = img[:, :, :c]
        return img

    def fill(self, indices: np.ndarray, split: str) -> Minibatch:
        h, w, c = self.target_shape
        data = np.zeros((len(indices), h, w, c), np.float32)
        labels = np.zeros(len(indices), np.int32)
        entries = self.index[split]
        for row, idx in enumerate(indices):
            path, label = entries[int(idx)]
            data[row] = self._load_one(path, h, w, c)
            labels[row] = label
        data = normalizers.apply(
            self.normalizer, data.reshape(len(indices), -1)
        ).reshape(data.shape)
        return Minibatch(
            data=data, labels=labels, targets=None, mask=None, indices=indices
        )
