"""Ensemble training: N model instances, aggregated evaluation.

Capability parity with ``veles/ensemble/`` [SURVEY.md 2.1 "Ensembles"]: the
reference trains N instances of a workflow (process-level task parallelism)
and aggregates their evaluation.  Here instances train sequentially in-process
(each gets its own derived seed) and predictions aggregate by mean probability
or majority vote.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.logger import Logger


class Ensemble(Logger):
    """Train ``n_models`` workflows built by ``build_fn()`` and aggregate.

    ``build_fn``: zero-arg callable returning a fresh (un-initialized)
    workflow with a ``model`` attribute (StandardWorkflow-style).
    """

    def __init__(
        self,
        build_fn: Callable[[], object],
        n_models: int = 5,
        *,
        base_seed: int = 1234,
    ):
        self.build_fn = build_fn
        self.n_models = n_models
        self.base_seed = base_seed
        self.workflows: List[object] = []
        self.decisions: List[object] = []

    def train(self, seeds: Optional[Sequence[int]] = None) -> List[object]:
        seeds = list(seeds) if seeds else [
            self.base_seed + 1000 * i for i in range(self.n_models)
        ]
        self.workflows, self.decisions = [], []
        # Members must differ by init/shuffle, NOT by task: pin the
        # "datasets" stream to one position for every build (a full
        # seed_all would hand each member a different synthetic dataset),
        # and reseed only the model-side streams per member.
        datasets_state = prng.get("datasets").state_dict()
        for i, seed in enumerate(seeds):
            # Reseed EVERY stream — including custom rand_name streams that
            # build_fn will only register DURING the build: seed_all sets the
            # global seed, so late-created generators derive member-specific
            # defaults too.  "datasets" is then re-pinned so all members
            # share one task (they must differ by init, not by data).
            prng.seed_all(seed)
            prng.get("datasets").load_state_dict(datasets_state)
            wf = self.build_fn()
            wf.initialize()
            dec = wf.run()
            self.info(
                "member %d/%d (seed %d): best=%s",
                i + 1, len(seeds), seed, dec.best_value,
            )
            self.workflows.append(wf)
            self.decisions.append(dec)
        return self.decisions

    # -- aggregation -------------------------------------------------------
    def predict_proba(self, x) -> jnp.ndarray:
        """Mean class probability over members (softmax-headed models)."""
        if not self.workflows:
            raise RuntimeError("train() first")
        probs = [
            wf.model.predict(wf.state.params, jnp.asarray(x))
            for wf in self.workflows
        ]
        return jnp.mean(jnp.stack(probs), axis=0)

    def predict(self, x, *, vote: str = "soft") -> np.ndarray:
        """``soft``: argmax of mean probs; ``hard``: majority vote."""
        if vote == "soft":
            return np.asarray(jnp.argmax(self.predict_proba(x), axis=1))
        votes = np.stack(
            [
                np.asarray(
                    jnp.argmax(
                        wf.model.predict(wf.state.params, jnp.asarray(x)),
                        axis=1,
                    )
                )
                for wf in self.workflows
            ]
        )  # [n_models, batch]
        n_classes = int(votes.max()) + 1
        counts = np.apply_along_axis(
            lambda col: np.bincount(col, minlength=n_classes), 0, votes
        )
        return counts.argmax(axis=0)

    def evaluate(self, split: str = "test") -> dict:
        """Aggregate error rate of the ensemble vs. the mean member.

        Each member's forward runs ONCE per batch; the ensemble vote and
        the per-member errors both derive from those probabilities.
        """
        loader = self.workflows[0].loader
        n_err, n, member_errs = 0, 0, np.zeros(len(self.workflows))
        # shuffle=False: evaluation must not advance the shuffle PRNG stream
        for mb in loader.batches(split, shuffle=False):
            valid = mb.mask > 0
            labels = mb.labels[valid]
            probs = [
                np.asarray(
                    wf.model.predict(wf.state.params, jnp.asarray(mb.data))
                )
                for wf in self.workflows
            ]
            ens_pred = np.mean(probs, axis=0).argmax(axis=1)[valid]
            n_err += int((ens_pred != labels).sum())
            n += int(valid.sum())
            for i, p in enumerate(probs):
                member_errs[i] += (p.argmax(axis=1)[valid] != labels).sum()
        return {
            "n_samples": n,
            "ensemble_err_pct": 100.0 * n_err / max(n, 1),
            "mean_member_err_pct": float(
                100.0 * member_errs.mean() / max(n, 1)
            ),
        }
