"""Ensemble training: N model instances, aggregated evaluation.

Capability parity with ``veles/ensemble/`` [SURVEY.md 2.1 "Ensembles"]: the
reference trains N instances of a workflow (process-level task parallelism)
and aggregates their evaluation.  Two modes here: :class:`Ensemble` trains
in-process sequentially from a ``build_fn`` (each member gets its own
derived seed), and :func:`train_from_module` trains members CONCURRENTLY in
spawned worker processes from a workflow-module path (the reference's
process-level mode) — deterministic given seeds and independent of worker
count.  Predictions aggregate by mean probability or majority vote.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Callable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.logger import Logger


class Ensemble(Logger):
    """Train ``n_models`` workflows built by ``build_fn()`` and aggregate.

    ``build_fn``: zero-arg callable returning a fresh (un-initialized)
    workflow with a ``model`` attribute (StandardWorkflow-style).
    """

    def __init__(
        self,
        build_fn: Callable[[], object],
        n_models: int = 5,
        *,
        base_seed: int = 1234,
    ):
        self.build_fn = build_fn
        self.n_models = n_models
        self.base_seed = base_seed
        self.workflows: List[object] = []
        self.decisions: List[object] = []

    def train(self, seeds: Optional[Sequence[int]] = None) -> List[object]:
        seeds = list(seeds) if seeds else [
            self.base_seed + 1000 * i for i in range(self.n_models)
        ]
        self.workflows, self.decisions = [], []
        # Members must differ by init/shuffle, NOT by task: pin the
        # "datasets" stream to one position for every build (a full
        # seed_all would hand each member a different synthetic dataset),
        # and reseed only the model-side streams per member.
        datasets_state = prng.get("datasets").state_dict()
        for i, seed in enumerate(seeds):
            # Reseed EVERY stream — including custom rand_name streams that
            # build_fn will only register DURING the build: seed_all sets the
            # global seed, so late-created generators derive member-specific
            # defaults too.  "datasets" is then re-pinned so all members
            # share one task (they must differ by init, not by data).
            prng.seed_all(seed)
            prng.get("datasets").load_state_dict(datasets_state)
            wf = self.build_fn()
            wf.initialize()
            dec = wf.run()
            self.info(
                "member %d/%d (seed %d): best=%s",
                i + 1, len(seeds), seed, dec.best_value,
            )
            self.workflows.append(wf)
            self.decisions.append(dec)
        return self.decisions

    # -- aggregation -------------------------------------------------------
    def predict_proba(self, x) -> jnp.ndarray:
        """Mean class probability over members (softmax-headed models)."""
        if not self.workflows:
            raise RuntimeError("train() first")
        probs = [
            wf.model.predict(wf.state.params, jnp.asarray(x))
            for wf in self.workflows
        ]
        return jnp.mean(jnp.stack(probs), axis=0)

    def predict(self, x, *, vote: str = "soft") -> np.ndarray:
        """``soft``: argmax of mean probs; ``hard``: majority vote."""
        if vote == "soft":
            return np.asarray(jnp.argmax(self.predict_proba(x), axis=1))
        votes = np.stack(
            [
                np.asarray(
                    jnp.argmax(
                        wf.model.predict(wf.state.params, jnp.asarray(x)),
                        axis=1,
                    )
                )
                for wf in self.workflows
            ]
        )  # [n_models, batch]
        n_classes = int(votes.max()) + 1
        counts = np.apply_along_axis(
            lambda col: np.bincount(col, minlength=n_classes), 0, votes
        )
        return counts.argmax(axis=0)

    def evaluate(self, split: str = "test") -> dict:
        """Aggregate error rate of the ensemble vs. the mean member.

        Each member's forward runs ONCE per batch; the ensemble vote and
        the per-member errors both derive from those probabilities.
        """
        loader = self.workflows[0].loader
        n_err, n, member_errs = 0, 0, np.zeros(len(self.workflows))
        # shuffle=False: evaluation must not advance the shuffle PRNG stream
        for mb in loader.batches(split, shuffle=False):
            valid = mb.mask > 0
            labels = mb.labels[valid]
            probs = [
                np.asarray(
                    wf.model.predict(wf.state.params, jnp.asarray(mb.data))
                )
                for wf in self.workflows
            ]
            ens_pred = np.mean(probs, axis=0).argmax(axis=1)[valid]
            n_err += int((ens_pred != labels).sum())
            n += int(valid.sum())
            for i, p in enumerate(probs):
                member_errs[i] += (p.argmax(axis=1)[valid] != labels).sum()
        return {
            "n_samples": n,
            "ensemble_err_pct": 100.0 * n_err / max(n, 1),
            "mean_member_err_pct": float(
                100.0 * member_errs.mean() / max(n, 1)
            ),
        }


def train_from_module(
    workflow_path: str,
    *,
    config_path: Optional[str] = None,
    n_models: int = 5,
    base_seed: int = 1234,
    n_workers: int = 1,
    stop_after: Optional[int] = None,
    device: Optional[str] = None,
) -> Ensemble:
    """Train ``n_models`` members of a workflow module concurrently in
    ``n_workers`` spawned processes (the reference's process-level ensemble
    mode).  Member i trains with seed ``base_seed + 1000*i`` in a fresh
    interpreter, so the result is deterministic given seeds and identical
    for every ``n_workers``.  Returns a fitted :class:`Ensemble` whose
    members share the parent's workflow (model/loader) but carry their own
    trained params — ``predict``/``evaluate`` work as usual.

    On a single shared accelerator pass ``device="cpu"`` — workers would
    contend for the one chip.
    """
    import pickle
    import tempfile

    from znicz_tpu.core.subproc import (
        _run_workflow_module,
        run_pool,
        train_member,
        warn_if_shared_accelerator,
    )

    parent_warned = warn_if_shared_accelerator(n_workers, device)
    seeds = [base_seed + 1000 * i for i in range(n_models)]
    with tempfile.TemporaryDirectory(prefix="znicz_ens_") as tmp:
        payloads = [
            {
                "workflow": workflow_path,
                "config": config_path,
                "seed": seed,
                "stop_after": stop_after,
                "device": device,
                "params_path": f"{tmp}/member_{i}.params",
            }
            for i, seed in enumerate(seeds)
        ]
        if payloads and n_workers > 1 and not parent_warned:
            # first worker checks contention from ITS backend (the parent
            # may never initialize one)
            payloads[0]["warn_n_workers"] = n_workers
        results = run_pool(train_member, payloads, n_workers)
        member_params = []
        for r in results:
            with open(r["params_path"], "rb") as f:
                member_params.append(pickle.load(f))
    # build the aggregation scaffold in-process (dry run: model + loader,
    # no training) and graft each member's trained params onto views of it.
    # Honor the caller's device choice only while it can still take effect:
    # a jax_platforms update on an already-initialized parent backend is at
    # best a no-op (the spawned workers above always honored it)
    try:
        from jax._src.xla_bridge import backends_are_initialized
    except ImportError:  # private API: assume initialized if it moves
        def backends_are_initialized():
            return True

    scaffold_device = device if not backends_are_initialized() else None
    launcher, _ = _run_workflow_module(
        workflow_path, config_path,
        seed=base_seed, stop_after=stop_after, device=scaffold_device,
        dry_run=True,
    )
    wf = launcher.workflow

    def _no_rebuild():
        raise RuntimeError(
            "this Ensemble's members were trained out-of-process; "
            "re-train via ensemble.train_from_module(...), not .train()"
        )

    ens = Ensemble(_no_rebuild, n_models=n_models, base_seed=base_seed)
    ens.workflows = [
        SimpleNamespace(
            model=wf.model,
            loader=wf.loader,
            state=SimpleNamespace(params=params),
        )
        for params in member_params
    ]
    ens.decisions = [
        SimpleNamespace(best_value=r["best_value"]) for r in results
    ]
    for i, (seed, r) in enumerate(zip(seeds, results)):
        ens.info(
            "member %d/%d (seed %d): best=%s", i + 1, n_models, seed,
            r["best_value"],
        )
    return ens
