"""Traced-context detection: which functions does JAX trace?

A function body runs under the tracer (so host-side Python is a hazard
there) when the function is:

* decorated with ``jax.jit`` / ``pjit`` / a ``partial(jax.jit, ...)``;
* passed by name to a transform (``jax.jit(f)``, ``jax.grad(f)``,
  ``jax.vmap``, ``jax.shard_map``, ``jax.checkpoint`` ...);
* passed by name to a ``lax`` control-flow combinator (``scan``,
  ``while_loop``, ``fori_loop``, ``cond``, ``switch``, ``map``);
* lexically nested inside any traced function (closures like a scan
  body defined inside a jitted step).

The index is built per module; the PROJECT-wide pass
(:mod:`znicz_tpu.analysis.project`) extends it across imports by
calling :meth:`TracedIndex.mark_traced` on the defining module's index
for every transform applied elsewhere (``jax.jit(workflow.step)`` in a
bench marks ``step`` traced in ``workflow``), and by chain-marking
module-level helpers reachable only from traced callers.  Static
arguments declared via ``static_argnums`` / ``static_argnames``
(literal values only) are excluded from the traced-parameter sets, so
branching on a static config flag inside a jitted function does not
fire ZNC001.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

# transform families: value args are traced callables
TRANSFORMS = {
    "jit",
    "pjit",
    "grad",
    "value_and_grad",
    "vmap",
    "pmap",
    "shard_map",
    "checkpoint",
    "remat",
    "custom_gradient",
}
# jit-like wrappers relevant to donation analysis (ZNC005)
JIT_WRAPPERS = {"jit", "pjit"}
# lax combinators: (call name) -> positional indices holding traced bodies
LAX_BODIES = {
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2, 3),
    "switch": (1, 2, 3, 4, 5, 6, 7),
    "map": (0,),
    "associative_scan": (0,),
}
# module paths whose members count as transform/combinator homes.
# Deliberately NOT "": from-imports are already alias-resolved to full
# dotted paths, and accepting bare names would conflate builtin map()
# (or any local def named jit/scan) with the jax combinators.
_MODULE_PATHS = {
    "jax",
    "lax",
    "jax.lax",
    "functools",
    "jax.experimental",
    "jax.experimental.shard_map",
    "jax.experimental.pjit",
    "znicz_tpu.core.compat",  # this repo's shard_map/pcast shims
}


def _basename(dotted: Optional[str]) -> Optional[str]:
    """``jax.lax.scan`` -> ``scan`` when the module path is a known
    transform home.  Unrelated dotted names (``self.fn``,
    ``jax.numpy.sum``) return None so an arbitrary attribute that
    happens to be called ``scan`` is not misread."""
    if dotted is None:
        return None
    head, _, last = dotted.rpartition(".")
    return last if head in _MODULE_PATHS else None


def _literal_tuple(node: ast.AST) -> Optional[Tuple]:
    """Literal int/str or tuple/list of them -> python tuple, else None."""
    if isinstance(node, ast.Constant) and isinstance(
        node.value, (int, str)
    ):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, (int, str)
            ):
                vals.append(elt.value)
            else:
                return None
        return tuple(vals)
    return None


def scope_local_names(fn) -> Set[str]:
    """Parameters plus every name the function itself binds — python
    scoping makes such a name local THROUGHOUT the function, so a load
    of it can never refer to a module-level def or variable."""
    names: Set[str] = set(_param_names(fn))
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(node.name)  # the def statement binds its name
            continue  # nested scopes bind their own names
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            names.add(node.id)
        stack.extend(ast.iter_child_nodes(node))
    return names


def name_is_shadowed(info, node: ast.AST, name: str) -> bool:
    """Is ``name``, read at ``node``, bound by an enclosing function
    scope (parameter, local assignment, nested def)?  A shadowed name
    can never resolve to the module-level def of the same name."""
    fn = info.enclosing_function(node)
    while fn is not None:
        if name in scope_local_names(fn):
            return True
        fn = info.enclosing_function(fn)
    return False


def name_is_param(info, node: ast.AST, name: str) -> bool:
    """Is ``name``, read at ``node``, a PARAMETER of an enclosing
    function?  ``jax.jit(step)`` inside ``def compile_it(step)`` wraps
    whatever the caller passed — never the module-level ``step`` def.
    (Weaker than :func:`name_is_shadowed` on purpose: nested-def names
    must stay resolvable for scan-body/closure patterns.)"""
    fn = info.enclosing_function(node)
    while fn is not None:
        if name in _param_names(fn):
            return True
        fn = info.enclosing_function(fn)
    return False


def unwrap_partial(info, node: ast.AST):
    """``partial(body, ...)`` -> ``(body, n_positional_bound,
    keyword_bound_names)``; anything else passes through with zero
    bindings.  The ONE owner of partial-unwrapping semantics — the
    per-module traced index and the project pass both call it, so the
    two can never diverge on what a partial binds."""
    if (
        isinstance(node, ast.Call)
        and _basename(info.resolved(node.func)) == "partial"
        and node.args
    ):
        kwnames = {kw.arg for kw in node.keywords if kw.arg}
        return node.args[0], len(node.args) - 1, kwnames
    return node, 0, set()


def _param_names(fn) -> List[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if args.vararg:
        names.append(args.vararg.arg)
    names += [a.arg for a in args.kwonlyargs]
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


def _positional_names(fn) -> List[str]:
    args = fn.args
    return [a.arg for a in args.posonlyargs + args.args]


def _static_names_from_kwargs(fn, keywords) -> Set[str]:
    """static_argnums / static_argnames keywords -> parameter names."""
    static: Set[str] = set()
    positional = _positional_names(fn)
    for kw in keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        vals = _literal_tuple(kw.value)
        if vals is None:
            continue
        for v in vals:
            if isinstance(v, str):
                static.add(v)
            elif isinstance(v, int) and 0 <= v < len(positional):
                static.add(positional[v])
    return static


class JitCall:
    """One resolvable jit/pjit application (decorator or call form)."""

    def __init__(self, node, fn, keywords):
        self.node = node  # the Call (or decorator) AST node to report on
        self.fn = fn  # the wrapped FunctionDef, when resolvable
        self.keywords = {kw.arg: kw.value for kw in keywords if kw.arg}

    def has_donation(self) -> bool:
        return (
            "donate_argnums" in self.keywords
            or "donate_argnames" in self.keywords
        )

    def static_names(self) -> Set[str]:
        if self.fn is None:
            return set()
        return _static_names_from_kwargs(
            self.fn,
            [
                ast.keyword(arg=k, value=v)
                for k, v in self.keywords.items()
            ],
        )


class TracedIndex:
    """Per-module index of traced functions and jit applications."""

    def __init__(self, info):
        self.info = info
        self._traced: Set[ast.AST] = set()
        # traced function -> statically-excluded parameter names
        self._static: Dict[ast.AST, Set[str]] = {}
        self.jit_calls: List[JitCall] = []
        self._defs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(info.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs_by_name.setdefault(node.name, []).append(node)
        self._index()

    # -- construction ----------------------------------------------------
    def _wrapper_call(self, call: ast.Call):
        """``jax.jit`` / ``partial(jax.jit, ...)`` call -> (base, kwargs);
        base is the transform's basename, kwargs the jit kwargs."""
        name = _basename(self.info.resolved(call.func))
        if name == "partial" and call.args:
            inner = _basename(self.info.resolved(call.args[0]))
            if inner in TRANSFORMS:
                return inner, list(call.keywords)
            return None, []
        if name in TRANSFORMS:
            return name, list(call.keywords)
        return None, []

    def _mark(self, fn, static: Set[str]) -> None:
        if fn in self._traced:
            self._static[fn] |= static
            return
        self._traced.add(fn)
        self._static[fn] = set(static)
        # closures defined inside a traced body are traced too
        for node in ast.walk(fn):
            if node is not fn and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                if node not in self._traced:
                    self._traced.add(node)
                    self._static[node] = set()

    def _visible_from(self, fn, site) -> bool:
        """Is ``fn``'s defining scope an ancestor of (or the module
        containing) ``site``?  A same-named def in a SIBLING function is
        a different object and must not be conflated."""
        enclosing = self.info.enclosing_function(fn)
        if enclosing is None:
            return True  # module-level def: visible everywhere
        cur = self.info.enclosing_function(site)
        while cur is not None:
            if cur is enclosing:
                return True
            cur = self.info.enclosing_function(cur)
        return False

    def _resolve_local(self, node, site=None) -> List[tuple]:
        """Callable AST node -> [(funcdef, partial_bound_names)],
        restricted to defs lexically visible from ``site``.

        ``partial(body, ...)`` (the repo's dominant way of handing
        configured bodies to shard_map/scan) unwraps to ``body``; the
        names the partial binds — keywords, plus the leading positional
        parameters — are trace-time CONSTANTS, so they join the static
        set rather than the traced one.
        """
        node, n_pos, kwnames = unwrap_partial(self.info, node)
        out = []
        if isinstance(node, ast.Name):
            if site is not None and name_is_param(
                self.info, site, node.id
            ):
                return []  # wraps whatever the caller passed in
            for fn in self._defs_by_name.get(node.id, []):
                if site is not None and not self._visible_from(fn, site):
                    continue
                bound = set(kwnames)
                bound.update(_positional_names(fn)[:n_pos])
                out.append((fn, bound))
        elif isinstance(node, ast.Lambda):
            out.append((node, set()))
        return out

    def _index(self) -> None:
        info = self.info
        # 1. decorator forms
        for name, defs in self._defs_by_name.items():
            for fn in defs:
                for dec in fn.decorator_list:
                    if isinstance(dec, ast.Call):
                        base, kws = self._wrapper_call(dec)
                        if base is None:
                            continue
                        static = _static_names_from_kwargs(fn, kws)
                        self._mark(fn, static)
                        if base in JIT_WRAPPERS:
                            self.jit_calls.append(JitCall(dec, fn, kws))
                    else:
                        base = _basename(info.resolved(dec))
                        if base in TRANSFORMS:
                            self._mark(fn, set())
                            if base in JIT_WRAPPERS:
                                self.jit_calls.append(JitCall(dec, fn, []))
        # 2. call forms: jax.jit(f, ...), jax.grad(f), lax.scan(body, ...)
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            base, kws = self._wrapper_call(node)
            if base is not None and node.args:
                resolved = self._resolve_local(node.args[0], node)
                for fn, bound in resolved:
                    static = set(bound)
                    if not isinstance(fn, ast.Lambda):
                        static |= _static_names_from_kwargs(fn, kws)
                    self._mark(fn, static)
                    if base in JIT_WRAPPERS and isinstance(
                        fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self.jit_calls.append(JitCall(node, fn, kws))
                if base in JIT_WRAPPERS and not resolved:
                    # unresolvable target (method, imported fn): keep the
                    # call so ZNC005 can still reason about kwargs
                    self.jit_calls.append(JitCall(node, None, kws))
                continue
            lax_name = _basename(info.resolved(node.func))
            body_slots = LAX_BODIES.get(lax_name or "")
            if body_slots:
                for i in body_slots:
                    if i < len(node.args):
                        for fn, bound in self._resolve_local(
                            node.args[i], node
                        ):
                            self._mark(fn, bound)

    # -- the project pass's entry point ----------------------------------
    def mark_traced(self, fn, static: Set[str]) -> None:
        """Mark ``fn`` traced with ``static`` parameter names excluded
        — the cross-module hook :mod:`znicz_tpu.analysis.project` uses
        when a transform application in ANOTHER module resolves to a
        def in this one.  Closures nested in ``fn`` are marked too,
        exactly like a same-module application."""
        self._mark(fn, static)

    # -- queries ---------------------------------------------------------
    def is_traced(self, fn) -> bool:
        return fn in self._traced

    def in_traced_code(self, node) -> bool:
        """True when the nearest enclosing function of ``node`` is traced."""
        fn = self.info.enclosing_function(node)
        return fn is not None and fn in self._traced

    def traced_param_names(self, node) -> Set[str]:
        """Union of non-static parameter names over the enclosing traced
        function chain — the names a branch condition must not consume."""
        names: Set[str] = set()
        fn = self.info.enclosing_function(node)
        while fn is not None:
            if fn in self._traced:
                names |= set(_param_names(fn)) - self._static.get(
                    fn, set()
                )
            fn = self.info.enclosing_function(fn)
        return names
