"""ZNC003: PartitionSpec / collective axis names the mesh doesn't declare.

The canonical mesh axes are declared once, in
``znicz_tpu/parallel/mesh.py`` (``DATA_AXIS = "data"`` ...).  A
``PartitionSpec("bacth")`` or ``psum(..., axis_name="dp")`` with an axis
the mesh never declares fails only at run time on a real mesh — or, for
collectives inside ``shard_map``, with an error message far from the
typo.  This rule cross-checks every string-literal axis name against
the declared constants.

The declared set is parsed from mesh.py's AST (no jax import); modules
are expected to reference the ``*_AXIS`` constants rather than repeat
the strings, so literal axis names in *other* modules are already a
smell — but a literal that matches a declared axis is accepted.
"""

from __future__ import annotations

import ast
import os
from typing import Optional, Set

from znicz_tpu.analysis.rules import Rule, register

# calls whose string args / axis kwargs name mesh axes
_SPEC_CALLS = {"jax.sharding.PartitionSpec", "PartitionSpec"}
_AXIS_KWARGS = {"axis_name", "axis_names", "axis"}
_COLLECTIVES = {
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "ppermute",
    "all_gather",
    "all_to_all",
    "axis_index",
    "psum_scatter",
    "pbroadcast",
}
# an attribute chain only counts as a jax collective / Mesh when it is
# rooted in a jax module — `client.all_gather("metrics")` is not one
_COLLECTIVE_HOMES = {"jax", "lax", "jax.lax"}
_MESH_HOMES = {"jax", "jax.sharding"}

_MESH_FILE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
    "parallel",
    "mesh.py",
)


def declared_axes(mesh_file: str = _MESH_FILE) -> Set[str]:
    """``*_AXIS = "name"`` string constants from mesh.py, by AST."""
    axes: Set[str] = set()
    try:
        with open(mesh_file, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        # missing/broken mesh.py: the rule degrades to a no-op by
        # design (check() returns early on an empty axis set)
        return axes
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ):
            if not isinstance(node.value.value, str):
                continue
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id.endswith(
                    "_AXIS"
                ):
                    axes.add(node.value.value)
    return axes


def _literal_axis_names(node: ast.AST):
    """String literals in a spec arg: "data", ("data", "model"), [..]."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value, node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, str
            ):
                yield elt.value, elt


@register
class ShardingAxisRule(Rule):
    id = "ZNC003"
    severity = "error"
    title = "PartitionSpec/collective axis name not declared by the mesh"

    example_support_files = {
        "znicz_tpu/parallel/mesh.py": 'DATA_AXIS = "data"\n'
    }
    example_fire = """
        from jax.sharding import PartitionSpec

        SPEC = PartitionSpec("bacth")
        """
    example_quiet = """
        from jax.sharding import PartitionSpec

        SPEC = PartitionSpec("data")
        """

    def __init__(self, axes: Optional[Set[str]] = None):
        self._fixed_axes = axes
        self._axes_by_root = {}

    def _axes_for(self, info) -> Set[str]:
        """Axis declarations of the TREE BEING ANALYZED: prefer
        ``<root>/znicz_tpu/parallel/mesh.py`` (a branch/worktree may
        legitimately declare more axes than this installed checkout),
        falling back to the analyzer's own sibling mesh.py."""
        if self._fixed_axes is not None:
            return self._fixed_axes
        key = getattr(info, "root", None) or ""
        if key not in self._axes_by_root:
            mesh_file = _MESH_FILE
            if key:
                candidate = os.path.join(
                    key, "znicz_tpu", "parallel", "mesh.py"
                )
                if os.path.exists(candidate):
                    mesh_file = candidate
            self._axes_by_root[key] = declared_axes(mesh_file)
        return self._axes_by_root[key]

    def _flag(self, info, node, axis, where, axes):
        return self.finding(
            info,
            node,
            f"axis name '{axis}' in {where} is not declared by "
            f"parallel/mesh.py (known: {', '.join(sorted(axes))}); "
            "reference the *_AXIS constants instead of string literals",
        )

    def check(self, info):
        axes = self._axes_for(info)
        if not axes:
            return  # mesh.py missing: nothing to check against
        if info.path.replace(os.sep, "/").endswith("parallel/mesh.py"):
            return  # the declaration site itself
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = info.resolved(node.func) or ""
            home, _, base = resolved.rpartition(".")
            if base in _COLLECTIVES and home not in _COLLECTIVE_HOMES:
                base = ""  # someone's own method, not a jax collective
            if base == "Mesh" and home not in _MESH_HOMES:
                base = ""
            if resolved in _SPEC_CALLS or base == "PartitionSpec":
                for arg in node.args:
                    for axis, site in _literal_axis_names(arg):
                        if axis not in axes:
                            yield self._flag(
                                info, site, axis, "PartitionSpec", axes
                            )
            if base in _COLLECTIVES or base == "Mesh":
                for kw in node.keywords:
                    if kw.arg in _AXIS_KWARGS:
                        for axis, site in _literal_axis_names(kw.value):
                            if axis not in axes:
                                yield self._flag(
                                    info, site, axis, f"{base}()", axes
                                )
                if base in _COLLECTIVES:
                    # positional axis_name (psum(x, "data") — the
                    # dominant calling convention): collectives take no
                    # other string arguments, so any literal is an axis
                    for arg in node.args:
                        for axis, site in _literal_axis_names(arg):
                            if axis not in axes:
                                yield self._flag(
                                    info, site, axis, f"{base}()", axes
                                )
                if base == "Mesh" and len(node.args) >= 2:
                    for axis, site in _literal_axis_names(node.args[1]):
                        if axis not in axes:
                            yield self._flag(
                                info, site, axis, "Mesh axis_names", axes
                            )
