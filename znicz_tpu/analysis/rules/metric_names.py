"""ZNC011: dynamically-constructed metric names.

A metric NAME is an identity: dashboards, alerts, the aggregator's
fleet merge and the SLO monitor all key on it.  Building one at a call
site from runtime values — ``counter(f"znicz_{kind}_total")``,
``gauge("znicz_" + name)``, ``histogram("znicz_%s_seconds" % phase)`` —
turns every distinct value into a NEW metric family: unbounded
exposition growth (the per-metric series cap doesn't see it — each name
is its own metric), series no query can aggregate over, and a fleet
merge that treats re-spellings of the same thing as different things.
The registry's own design says where the value belongs: a **label** on
one statically-named family (labels are capped, mergeable and
queryable).

The rule flags a call to ``counter`` / ``gauge`` / ``histogram`` —
bare or as an attribute (``observability.counter``,
``registry.histogram``, ``self._registry.counter``) — whose name
argument is PROVABLY dynamic text:

* an f-string with at least one interpolation,
* a ``+`` / ``%`` expression with a string literal (or f-string) on
  either side,
* a ``"...".format(...)`` call.

A plain variable stays quiet (its value may well be a static constant
— e.g. ``PhaseTimer`` passing its ``metric`` parameter through); the
rule targets the call sites where the dynamism is visible.  A genuine
exception is exempted inline with ``# znicz-check: disable=ZNC011``
and a reason.
"""

from __future__ import annotations

import ast
from typing import Iterable

from znicz_tpu.analysis.rules import Rule, register

_FACTORIES = ("counter", "gauge", "histogram")


def _stringish(node: ast.AST) -> bool:
    """A node that is definitely a str at runtime."""
    return (
        isinstance(node, ast.Constant) and isinstance(node.value, str)
    ) or isinstance(node, ast.JoinedStr)


def _dynamic_name(node: ast.AST) -> bool:
    """Provably runtime-constructed text."""
    if isinstance(node, ast.JoinedStr):
        return any(
            isinstance(v, ast.FormattedValue) for v in node.values
        )
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.Add, ast.Mod)
    ):
        return _stringish(node.left) or _stringish(node.right)
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "format"
        and _stringish(node.func.value)
    ):
        return True
    return False


@register
class DynamicMetricNameRule(Rule):
    id = "ZNC011"
    severity = "warning"
    title = (
        "dynamically-constructed metric name (unbounded families: put "
        "the varying value in a label, keep the name static)"
    )

    example_fire = """
        from znicz_tpu import observability

        def track(kind):
            observability.counter(f"znicz_{kind}_total").inc()
        """
    example_quiet = """
        from znicz_tpu import observability

        def track(kind):
            observability.counter(
                "znicz_events_total", "events"
            ).labels(kind=kind).inc()
        """

    def check(self, info) -> Iterable:
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                callee = func.attr
            elif isinstance(func, ast.Name):
                callee = func.id
            else:
                continue
            if callee not in _FACTORIES:
                continue
            name_arg = None
            if node.args:
                name_arg = node.args[0]
            else:
                for kw in node.keywords:
                    if kw.arg == "name":
                        name_arg = kw.value
                        break
            if name_arg is None or not _dynamic_name(name_arg):
                continue
            yield self.finding(
                info,
                node,
                f"{callee}() name is built at runtime — every distinct "
                "value becomes a new uncapped metric family that "
                "nothing can aggregate; use a static name with the "
                "value as a label (labels are cardinality-capped and "
                "fleet-mergeable), or pragma-exempt with a reason",
            )
