"""ZNC012: lock-discipline races in the serving tier.

Every race the serving tier has shipped so far (the SLOMonitor ring
mutated during iteration, the router's request tally, ``rank()``
reading two affinity-index states) had the same shape: a class that
owns (or is driven by) threads protects an attribute with ``with
self._lock`` in SOME methods and touches it bare in others.  Human
review caught each one; this rule makes the pattern mechanical.

Scope: classes in ``services/``, ``cluster/`` and ``observability/``
modules that declare at least one lock attribute (``self.X =
threading.Lock()`` / ``RLock()`` / ``Condition()``, or an attribute
with "lock" in its name used as a ``with self.X:`` context — the lock
is the author's own declaration that the class is shared).  For each
such class the rule:

* collects every ``self.<attr>`` access per method, classified as
  **write** (assignment / augmented assignment), **mutate** (a call of
  a known container mutator — ``append``, ``pop``, ``update``,
  ``clear``, ... — or a subscript store/delete), **iterate**
  (``for x in self.a``, a comprehension source, ``list(self.a)`` /
  ``sorted(...)`` / ``.values()``-family views) or **read** (anything
  else);
* computes which *thread roots* reach each method: a
  ``threading.Thread(target=self.m)`` target seeds a per-thread root,
  public methods (and dunders other than ``__init__``) seed the
  many-threaded ``client`` root, and roots propagate along the
  intra-class ``self.m()`` call graph;
* treats a private method whose every intra-class call site holds the
  lock as lock-held itself (the repo's documented "lock held by the
  caller" convention);
* fires on any **bare write/mutate/iterate** of an attribute that is
  accessed under the lock somewhere else, when the attribute's
  audience spans more than one root (or the inherently concurrent
  ``client`` root alone).

Stays quiet on: plain reads (attribute loads are atomic in CPython —
reading a lock-guarded counter without the lock is stale, not torn),
``__init__`` writes (the object is not shared yet), attributes only
ever touched by one dedicated thread, and classes with no lock (they
declare no discipline to violate).  A deliberate bare access (e.g. an
atomic flag store) is exempted inline with
``# znicz-check: disable=ZNC012 -- <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, NamedTuple, Optional, Set

from znicz_tpu.analysis.rules import Rule, register

_LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
}
_MUTATORS = {
    "append",
    "appendleft",
    "extend",
    "extendleft",
    "insert",
    "remove",
    "pop",
    "popleft",
    "popitem",
    "clear",
    "update",
    "add",
    "discard",
    "setdefault",
    "sort",
    "reverse",
}
# calls that drain their (sole) iterable argument
_ITER_CALLS = {"list", "tuple", "set", "sorted", "frozenset", "dict"}
# attribute calls returning live iteration views
_VIEW_CALLS = {"values", "keys", "items"}

_KIND_VERB = {
    "write": "written",
    "mutate": "mutated",
    "iterate": "iterated",
}


class _Access(NamedTuple):
    attr: str
    method: str
    node: ast.AST
    kind: str
    locked: bool


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ClassModel:
    """Per-class indexes the detector reasons over."""

    def __init__(self, info, cls: ast.ClassDef):
        self.info = info
        self.cls = cls
        self.methods: Dict[str, ast.AST] = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.lock_attrs = self._find_lock_attrs()
        self.thread_targets = self._find_thread_targets()
        # self.m() call sites: method -> [(callee, locked)]
        self.calls: Dict[str, List] = {m: [] for m in self.methods}
        self.accesses: List[_Access] = []
        if self.lock_attrs:
            for name, fn in self.methods.items():
                self._scan_method(name, fn)
        self.lock_held = self._lock_held_methods()
        self.roots = self._method_roots()

    # -- structure discovery ----------------------------------------------

    def _find_lock_attrs(self) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(self.cls):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                resolved = self.info.resolved(node.value.func)
                if resolved in _LOCK_FACTORIES:
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr:
                            out.add(attr)
            elif isinstance(node, ast.With):
                # a lock handed in from outside (``self._lock =
                # registry._lock``) still declares the discipline when
                # it is USED as one
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr and "lock" in attr.lower():
                        out.add(attr)
        return out

    def _find_thread_targets(self) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(self.cls):
            if not isinstance(node, ast.Call):
                continue
            if self.info.resolved(node.func) != "threading.Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    attr = _self_attr(kw.value)
                    if attr:
                        out.add(attr)
        return out

    # -- per-method scanning ----------------------------------------------

    def _is_locked(self, node: ast.AST, fn: ast.AST) -> bool:
        cur = self.info.parents.get(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    attr = _self_attr(item.context_expr)
                    if attr in self.lock_attrs:
                        return True
            cur = self.info.parents.get(cur)
        return False

    def _classify(self, node: ast.Attribute) -> str:
        parents = self.info.parents
        parent = parents.get(node)
        # self.a = v / self.a += v / self.a: T = v
        if isinstance(parent, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                parent.targets
                if isinstance(parent, ast.Assign)
                else [parent.target]
            )
            if node in targets:
                return "write"
        if isinstance(parent, ast.Tuple) and isinstance(
            node.ctx, ast.Store
        ):
            return "write"  # tuple-unpacking target
        # self.a[k] = v / del self.a[k]
        if (
            isinstance(parent, ast.Subscript)
            and parent.value is node
            and isinstance(parent.ctx, (ast.Store, ast.Del))
        ):
            return "mutate"
        # self.a.append(...) and friends; .values()/.keys()/.items()
        if (
            isinstance(parent, ast.Attribute)
            and parent.value is node
            and isinstance(parents.get(parent), ast.Call)
            and parents.get(parent).func is parent
        ):
            if parent.attr in _MUTATORS:
                return "mutate"
            if parent.attr in _VIEW_CALLS:
                return "iterate"
        # for x in self.a / comprehension over self.a
        if isinstance(parent, (ast.For, ast.AsyncFor)) and parent.iter is node:
            return "iterate"
        if isinstance(parent, ast.comprehension) and parent.iter is node:
            return "iterate"
        # list(self.a), sorted(self.a), ...
        if (
            isinstance(parent, ast.Call)
            and node in parent.args
            and isinstance(parent.func, ast.Name)
            and parent.func.id in _ITER_CALLS
        ):
            return "iterate"
        return "read"

    def _scan_method(self, name: str, fn: ast.AST) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee in self.methods:
                    self.calls[name].append(
                        (callee, self._is_locked(node, fn))
                    )
            attr = _self_attr(node)
            if (
                attr is None
                or attr in self.lock_attrs
                or attr in self.methods
            ):
                continue
            self.accesses.append(
                _Access(
                    attr,
                    name,
                    node,
                    self._classify(node),
                    self._is_locked(node, fn),
                )
            )

    # -- derived facts -----------------------------------------------------

    def _lock_held_methods(self) -> Set[str]:
        """Private methods whose every intra-class call site holds the
        lock (>= 1 site): their bodies run under the caller's lock."""
        held: Set[str] = set()
        changed = True
        while changed:
            changed = False
            incoming: Dict[str, List[bool]] = {}
            for caller, edges in self.calls.items():
                for callee, locked in edges:
                    incoming.setdefault(callee, []).append(
                        locked or caller in held
                    )
            for name in self.methods:
                if name in held or not name.startswith("_"):
                    continue
                if name in self.thread_targets or name.startswith("__"):
                    continue
                sites = incoming.get(name, [])
                if sites and all(sites):
                    held.add(name)
                    changed = True
        return held

    def _method_roots(self) -> Dict[str, Set[str]]:
        roots: Dict[str, Set[str]] = {m: set() for m in self.methods}
        for name in self.methods:
            if name == "__init__":
                continue
            if name in self.thread_targets:
                roots[name].add(f"thread:{name}")
            elif not name.startswith("_") or (
                name.startswith("__") and name.endswith("__")
            ):
                roots[name].add("client")
        changed = True
        while changed:
            changed = False
            for caller, edges in self.calls.items():
                for callee, _ in edges:
                    if callee in roots and not roots[caller] <= roots[
                        callee
                    ]:
                        roots[callee] |= roots[caller]
                        changed = True
        return roots


@register
class LockDisciplineRule(Rule):
    id = "ZNC012"
    severity = "warning"
    title = (
        "lock-guarded attribute accessed without the lock in a "
        "multi-threaded serving-tier class"
    )

    example_path = "services/mod.py"
    example_fire = """
        import threading

        class Door:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = []

            def submit(self, item):
                with self._lock:
                    self._pending.append(item)

            def drain(self):
                out = list(self._pending)
                return out
        """
    example_quiet = """
        import threading

        class Door:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = []

            def submit(self, item):
                with self._lock:
                    self._pending.append(item)

            def drain(self):
                with self._lock:
                    out = list(self._pending)
                return out
        """

    def _in_scope(self, info) -> bool:
        # ONE owner of the serving-tier scope (lockmodel.SERVING_SCOPES)
        # — a new serving package widens every concurrency rule at once
        from znicz_tpu.analysis.lockmodel import in_serving_scope

        return in_serving_scope(info)

    def check(self, info) -> Iterable:
        if not self._in_scope(info):
            return
        for cls in info.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            model = _ClassModel(info, cls)
            if not model.lock_attrs:
                continue
            # an attribute nobody writes after __init__ is immutable
            # config: iterating it bare cannot race (windows tuples,
            # label-name tuples), whatever lock its neighbours hold
            mutable_attrs = {
                acc.attr
                for acc in model.accesses
                if acc.kind in ("write", "mutate")
                and acc.method != "__init__"
            }
            guarded: Dict[str, List[_Access]] = {}
            for acc in model.accesses:
                if acc.attr not in mutable_attrs:
                    continue
                if acc.locked or acc.method in model.lock_held:
                    guarded.setdefault(acc.attr, []).append(acc)
            if not guarded:
                continue
            audience: Dict[str, Set[str]] = {}
            for acc in model.accesses:
                if acc.attr in guarded and acc.method != "__init__":
                    audience.setdefault(acc.attr, set()).update(
                        model.roots.get(acc.method, set())
                    )
            for acc in model.accesses:
                if (
                    acc.attr not in guarded
                    or acc.locked
                    or acc.method in model.lock_held
                    or acc.method == "__init__"
                    or acc.kind not in _KIND_VERB
                ):
                    continue
                aud = audience.get(acc.attr, set())
                if not (len(aud) >= 2 or aud == {"client"}):
                    continue  # a single dedicated thread: no race
                lock = sorted(model.lock_attrs)[0]
                where = sorted(
                    {
                        g.method
                        for g in guarded[acc.attr]
                    }
                )
                yield self.finding(
                    info,
                    acc.node,
                    f"'self.{acc.attr}' is {_KIND_VERB[acc.kind]} here "
                    f"without the lock, but is guarded by "
                    f"'self.{lock}' in {', '.join(where)} and reachable "
                    f"from {', '.join(sorted(aud))}; hold the lock (or "
                    "pragma-exempt an intentionally atomic access with "
                    "a reason)",
                )
