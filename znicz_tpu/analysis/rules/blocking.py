"""ZNC010: unbounded blocking primitives in the serving tier
(``services/`` and ``cluster/``).

The serving stack's contract is "no hung clients, ever"
(docs/SERVING.md): every wait the front door, the HTTP layer, the
engine thread, or the cluster router/registry performs must be
BOUNDED, because a missing timeout turns any dropped wake-up, dead
peer, or wedged thread into a silent permanent hang — the exact
failure the watchdog (and the router's heartbeat ladder) exists to
catch.  This rule flags the stdlib blocking calls that default to
"wait forever" when they appear in a ``services/`` or ``cluster/``
module with no ``timeout``:

* ``queue.Queue.get()`` (``.get_nowait()`` / ``.get(timeout=...)`` /
  ``.get(block=False)`` are fine)
* ``threading.Event.wait()`` / ``Condition.wait()``
* ``Thread.join()``
* ``Lock.acquire()`` (``acquire(False)`` / ``acquire(blocking=False)``
  / ``acquire(timeout=...)`` are fine)

Detection is conservative to stay quiet on the common non-blocking
homonyms: a call fires only when it is an ATTRIBUTE call with ZERO
positional arguments and none of the ``timeout`` / ``block`` /
``blocking`` keywords — so ``", ".join(parts)``, ``d.get(key)``,
``lock.acquire(False)`` and ``t.join(grace)`` never fire — and only in
modules under a ``services/`` or ``cluster/`` path (hot training-loop
code is free to block on purpose; the serving tier is not).  Attribute
chains that
resolve to an imported MODULE (``os.wait()``) are skipped: the rule
targets object-level synchronization primitives.

A deliberate unbounded wait (rare; say why) is exempted inline with
``# znicz-check: disable=ZNC010 -- <reason>``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from znicz_tpu.analysis.rules import Rule, register

_BLOCKING_METHODS = ("get", "wait", "join", "acquire")
_ESCAPE_KEYWORDS = ("timeout", "block", "blocking")


@register
class UnboundedBlockingRule(Rule):
    id = "ZNC010"
    severity = "warning"
    title = (
        "unbounded blocking call in the serving tier (pass a timeout: "
        "a missing one turns a lost wake-up into a permanent hang)"
    )

    example_path = "services/mod.py"
    example_fire = """
        import queue

        class Worker:
            def __init__(self):
                self.q = queue.Queue()

            def next_item(self):
                return self.q.get()
        """
    example_quiet = """
        import queue

        class Worker:
            def __init__(self):
                self.q = queue.Queue()

            def next_item(self):
                return self.q.get(timeout=1.0)
        """

    # the serving tier: every package whose threads a hung wait strands
    # a CLIENT in, not just a batch job
    _SCOPES = ("/services/", "/cluster/")

    def _in_services(self, info) -> bool:
        path = f"/{info.path}".replace("\\", "/")
        return any(scope in path for scope in self._SCOPES)

    def check(self, info) -> Iterable:
        if not self._in_services(info):
            return
        for node in ast.walk(info.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_METHODS
            ):
                continue
            if node.args:
                continue  # ", ".join(parts), d.get(k), acquire(False)
            if any(kw.arg in _ESCAPE_KEYWORDS for kw in node.keywords):
                continue
            # module-level functions (os.wait(), loader.join()) are not
            # synchronization objects — skip resolvable module bases
            base = node.func.value
            if isinstance(base, ast.Name) and (
                base.id in info.import_aliases
                or base.id in info.from_imports
            ):
                continue
            yield self.finding(
                info,
                node,
                f".{node.func.attr}() with no timeout blocks forever "
                "if the wake-up never comes; pass timeout= (loop if "
                "the wait is logically unbounded) or pragma-exempt "
                "with a reason",
            )
