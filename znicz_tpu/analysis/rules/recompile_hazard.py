"""ZNC014: unbounded-dynamic values reaching recompile-sensitive sinks.

The serving stack's hardest-won invariant is **zero new compiled
programs** under arbitrary request streams (docs/SERVING.md): every
quantity that becomes a compiled-program identity must be snapped onto
a small fixed ladder first.  A dozen engine/frontdoor/cluster tests
pin the invariant at runtime; this rule proves the discipline
statically, using the dataflow layer's provenance lattice
(:mod:`znicz_tpu.analysis.dataflow`).

A finding fires when a value classified **unbounded-dynamic**
(``len(...)``, a wall-clock read, a loop counter, an array ``.size``,
or anything those taint through assignments, helper returns, call
arguments and attribute-field stores) reaches one of the
recompile-sensitive sinks WITHOUT passing a bucketing boundary
(``bucket_for``, the x2 window/rung helpers, ``min(x, BOUND)``
clamps, or any helper whose return provenance is bounded):

* a ``static_argnums``/``static_argnames`` argument at a call site of
  a jit-compiled function (decorator or ``fast = jax.jit(f,
  static_...)`` call form, resolved cross-module) — each distinct
  static value IS a new executable;
* a **program-cache / ladder key**: the key argument of the engines'
  ``_program``/``_timed_program`` ledger calls, or a subscript
  store/``setdefault`` into a container whose name contains
  ``program``/``ladder``/``cache`` — an unbounded key grows the cache
  (and the compiled-program count it fronts) with the request stream;
* a host-side **shape constructor**: ``numpy``/``jax.numpy``
  ``zeros``/``ones``/``full``/``empty``/``arange`` dims or a
  ``.reshape(...)`` argument — a host buffer sized by request data
  hands every jit call a fresh shape to compile for.

Sinks inside TRACED code stay quiet (``jnp.zeros(x.shape)`` under jit
is shape-polymorphic tracing, not a host recompile driver), and only
definitely-unbounded values fire — unknown provenance never does, so
config plumbing stays silent.  An intentional per-geometry compile
(e.g. a cache deliberately keyed by caller-controlled batch size) is
exempted inline with ``# znicz-check: disable=ZNC014 -- <reason>``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from znicz_tpu.analysis.context import (
    JIT_WRAPPERS,
    _positional_names,
    _static_names_from_kwargs,
    name_is_shadowed,
)
from znicz_tpu.analysis.dataflow import UNBOUNDED, get_dataflow
from znicz_tpu.analysis.lockmodel import in_serving_scope
from znicz_tpu.analysis.rules import Rule, register

_LEDGER_CALLS = {"_program", "_timed_program"}
_CACHE_NAME_RE = re.compile(r"(program|ladder|cache)", re.I)
_SHAPE_CTORS = {
    f"{mod}.{fn}"
    for mod in ("numpy", "jax.numpy")
    for fn in ("zeros", "ones", "full", "empty", "arange")
}


def _terminal_name(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


@register
class RecompileHazardRule(Rule):
    id = "ZNC014"
    severity = "warning"
    project = True
    title = (
        "unbounded-dynamic value reaches a compile-identity sink "
        "(static arg, program-cache key, host shape) without a "
        "bucketing boundary"
    )

    example_path = "services/mod.py"
    example_fire = """
        programs = {}

        def admit(prompt):
            key = ("admit", len(prompt))
            programs[key] = 1
        """
    example_quiet = """
        LADDER = (16, 32, 64)
        programs = {}

        def bucket_for(n, ladder):
            for rung in ladder:
                if n <= rung:
                    return rung
            return ladder[-1]

        def admit(prompt):
            key = ("admit", bucket_for(len(prompt), LADDER))
            programs[key] = 1
        """

    # -- static-argument registry -------------------------------------------

    def _static_registry(self, index):
        """id(fn) -> (fn, static param names) for every resolvable jit
        application with static args, plus per-module alias maps for
        ``fast = jax.jit(f, static_...)`` bindings."""
        static_fns: Dict[int, Tuple[ast.AST, Set[str]]] = {}
        aliases: Dict[int, Dict[str, Tuple[ast.AST, Set[str]]]] = {}
        for info in index.modules.values():
            mod_aliases: Dict[str, Tuple[ast.AST, Set[str]]] = {}
            for jc in info.traced.jit_calls:
                if jc.fn is None:
                    continue
                names = jc.static_names()
                if names:
                    prior = static_fns.get(id(jc.fn))
                    if prior is not None:
                        names = names | prior[1]
                    static_fns[id(jc.fn)] = (jc.fn, names)
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Assign):
                    continue
                call = node.value
                if not isinstance(call, ast.Call):
                    continue
                base, kws = info.traced._wrapper_call(call)
                if base not in JIT_WRAPPERS or not call.args:
                    continue
                targets = []
                for tinfo, fn, _bound in index._resolve_callable(
                    info, call.args[0]
                ):
                    targets.append(fn)
                for fn, _bound in info.traced._resolve_local(
                    call.args[0], call
                ):
                    targets.append(fn)
                for fn in targets:
                    if isinstance(fn, ast.Lambda):
                        continue
                    names = _static_names_from_kwargs(fn, kws)
                    if not names:
                        continue
                    prior = static_fns.get(id(fn))
                    if prior is not None:
                        names = names | prior[1]
                    static_fns[id(fn)] = (fn, names)
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            mod_aliases[t.id] = (fn, names)
            if mod_aliases:
                aliases[id(info)] = mod_aliases
        return static_fns, aliases

    # -- sink scan ----------------------------------------------------------

    def project_check(self, index) -> Iterable:
        df = get_dataflow(index)
        static_fns, aliases = self._static_registry(index)
        findings = []
        for info in index.modules.values():
            findings.extend(
                self._scan_module(info, index, df, static_fns, aliases)
            )
        return findings

    def _fire(self, info, node, prov, sink_desc):
        return self.finding(
            info,
            node,
            f"unbounded-dynamic value ({prov.origin}) reaches "
            f"{sink_desc} without passing a bucketing boundary — each "
            "distinct value compiles (or caches) a new program; snap "
            "it up a ladder rung (bucket_for / a *_window helper) or "
            "derive it from static config",
        )

    def _check_expr(self, expr, info, df, out, node, sink_desc):
        elts = (
            expr.elts
            if isinstance(expr, (ast.Tuple, ast.List))
            else [expr]
        )
        for elt in elts:
            p = df.prov(elt, info)
            if p.level == UNBOUNDED:
                out.append(self._fire(info, node, p, sink_desc))
                return

    def _scan_module(self, info, index, df, static_fns, aliases):
        out: List = []
        mod_aliases = aliases.get(id(info), {})
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Call):
                if info.traced.in_traced_code(node):
                    continue  # traced shapes are trace-polymorphism
                self._scan_call(
                    node, info, index, df, static_fns, mod_aliases, out
                )
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                if info.traced.in_traced_code(node):
                    continue
                name = _terminal_name(node.value)
                if _CACHE_NAME_RE.search(name):
                    self._check_expr(
                        node.slice,
                        info,
                        df,
                        out,
                        node,
                        f"the key of cache/ladder '{name}'",
                    )
        return out

    def _scan_call(
        self, node, info, index, df, static_fns, mod_aliases, out
    ):
        func = node.func
        # 1. program-ledger calls: first arg is the cache key
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _LEDGER_CALLS
            and node.args
        ):
            self._check_expr(
                node.args[0],
                info,
                df,
                out,
                node,
                f"the program-ledger key of .{func.attr}()",
            )
            return
        # 2. .setdefault(key, ...) on cache-named containers
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "setdefault"
            and node.args
        ):
            name = _terminal_name(func.value)
            if _CACHE_NAME_RE.search(name):
                self._check_expr(
                    node.args[0],
                    info,
                    df,
                    out,
                    node,
                    f"the key of cache/ladder '{name}'",
                )
            return
        # 3. host-side shape constructors — SERVING tier only: a
        # loader materializing a dataset-sized host buffer is a
        # one-time allocation, not a per-request compile driver; the
        # zero-new-programs contract lives where requests flow
        if in_serving_scope(info):
            resolved = info.resolved(func)
            if resolved in _SHAPE_CTORS and node.args:
                self._check_expr(
                    node.args[0],
                    info,
                    df,
                    out,
                    node,
                    f"the shape of host-side {resolved}()",
                )
                return
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "reshape"
                and node.args
            ):
                for a in node.args:
                    self._check_expr(
                        a, info, df, out, node, "a .reshape() dimension"
                    )
                return
        # 4. static arguments at call sites of jit-compiled functions
        target = None
        if isinstance(func, ast.Name):
            if func.id in mod_aliases and not name_is_shadowed(
                info, func, func.id
            ):
                target = mod_aliases[func.id]
        if target is None and isinstance(func, (ast.Name, ast.Attribute)):
            if not (
                isinstance(func, ast.Name)
                and name_is_shadowed(info, func, func.id)
            ):
                hit = index.resolve_symbol(info.resolved(func), home=info)
                if hit is not None and hit[1] is not None:
                    entry = static_fns.get(id(hit[1]))
                    if entry is not None:
                        target = entry
        if target is None:
            return
        fn, static_names = target
        pos = _positional_names(fn)
        for i, arg in enumerate(node.args):
            if isinstance(arg, ast.Starred):
                break
            if i < len(pos) and pos[i] in static_names:
                self._check_expr(
                    arg,
                    info,
                    df,
                    out,
                    node,
                    f"static argument '{pos[i]}' of a jit-compiled "
                    "function",
                )
        for kw in node.keywords:
            if kw.arg in static_names:
                self._check_expr(
                    kw.value,
                    info,
                    df,
                    out,
                    node,
                    f"static argument '{kw.arg}' of a jit-compiled "
                    "function",
                )
