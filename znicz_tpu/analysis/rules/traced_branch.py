"""ZNC001: Python control flow branching on traced values.

``if``/``while`` (and conditional expressions) on a traced array raise
``ConcretizationTypeError`` at trace time in the best case; in the worst
case (shape-dependent code that happens to concretize) they silently
bake one branch into the compiled program.  Inside jitted code the
data-dependent form is ``jnp.where`` / ``lax.cond`` / ``lax.select``.

Approximation: a condition is suspect when it *consumes the value* of a
non-static parameter of the enclosing traced function chain.  Reading
trace-time-concrete properties is fine and excluded: ``x is None``,
``isinstance(x, ...)``, ``hasattr``, ``len(x)``, ``callable``, and the
``.shape`` / ``.ndim`` / ``.dtype`` / ``.size`` attributes.
"""

from __future__ import annotations

import ast
from typing import List, Set

from znicz_tpu.analysis.rules import Rule, register

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}
_STATIC_CALLS = {"isinstance", "hasattr", "len", "callable", "getattr", "type"}


def _value_usages(test: ast.AST, traced: Set[str]) -> List[str]:
    """Traced names whose *value* the condition consumes."""
    skip: Set[ast.AST] = set()

    for node in ast.walk(test):
        # `x is None` / `x is not None`: a concrete Python identity check
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            if all(
                isinstance(c, ast.Constant)
                for c in node.comparators
            ):
                skip.update(ast.walk(node))
        # len(x), isinstance(x, T), hasattr(x, a): trace-time concrete
        elif isinstance(node, ast.Call):
            name = node.func.id if isinstance(node.func, ast.Name) else None
            if name in _STATIC_CALLS:
                skip.update(ast.walk(node))
        # x.ndim == 4, x.shape[0] ...: static under tracing
        elif isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            skip.update(ast.walk(node))

    hits: List[str] = []
    for node in ast.walk(test):
        if node in skip:
            continue
        if isinstance(node, ast.Name) and node.id in traced:
            hits.append(node.id)
    return sorted(set(hits))


@register
class TracedBranchRule(Rule):
    id = "ZNC001"
    severity = "error"
    title = "Python if/while on a traced value inside jitted code"

    example_fire = """
        import jax

        @jax.jit
        def step(x):
            if x > 0:
                return x
            return -x
        """
    example_quiet = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.where(x > 0, x, -x)
        """

    def check(self, info):
        for node in ast.walk(info.tree):
            if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                continue
            if not info.traced.in_traced_code(node):
                continue
            traced = info.traced.traced_param_names(node)
            names = _value_usages(node.test, traced)
            if names:
                kind = {
                    ast.If: "if",
                    ast.While: "while",
                    ast.IfExp: "conditional expression",
                }[type(node)]
                yield self.finding(
                    info,
                    node,
                    f"{kind} branches on traced value(s) "
                    f"{', '.join(names)} inside a jitted/traced function; "
                    "use jnp.where or lax.cond, or declare the argument "
                    "static",
                )
