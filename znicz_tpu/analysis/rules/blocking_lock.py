"""ZNC016: blocking operations performed while holding a lock.

A serving-tier lock is a convoy waiting to happen: every critical
section's wall time is paid by EVERY thread that needs the lock — the
engine tick, the HTTP workers, the watchdog, the metrics pusher.  A
lock held across a bounded wait stalls the tier for the bound; held
across an unbounded one (a socket with no timeout, a wedged device
sync) it turns one slow peer into a fleet-wide hang that the watchdog
cannot break, because the watchdog's own probe needs the same lock.
The repo's discipline is "compute under the lock, wait outside it"
(snapshot state under the lock, then do I/O on the copy).

This rule walks every serving-tier method with the shared lock model
(:mod:`znicz_tpu.analysis.lockmodel`) and fires when a recognized
blocking operation runs while any ``with self.<lock>:`` is held —
directly, or transitively through calls resolved via the PR 9 call
graph (``self.m()``, typed ``self.attr.m()``, plain project
functions; the call chain is named in the message).  Recognized
blocking operations: ``time.sleep``, HTTP/socket calls
(``urlopen``, ``create_connection``, ``.getresponse()``, ``.recv()``,
``.accept()``, ``.sendall()``), subprocess spawns, ``open()`` file
I/O, device syncs (``jax.device_get``, ``.block_until_ready()``), and
synchronization waits (``.get()``/``.wait()``/``.join()`` in ZNC010's
homonym-safe shape) — **with or without a timeout**: a bounded wait
under a lock is still a bounded stall of every other thread.

A deliberate short wait under a lock (rare; say why, and bound it) is
exempted inline with ``# znicz-check: disable=ZNC016 -- <reason>``.
"""

from __future__ import annotations

from typing import Iterable

from znicz_tpu.analysis.lockmodel import get_lockflow
from znicz_tpu.analysis.rules import Rule, register


@register
class BlockingUnderLockRule(Rule):
    id = "ZNC016"
    severity = "warning"
    project = True
    title = (
        "blocking operation while holding a serving-tier lock "
        "(every thread needing the lock stalls for the wait)"
    )

    example_path = "services/mod.py"
    example_fire = """
        import threading
        import time

        class Door:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def tick(self):
                with self._lock:
                    time.sleep(0.05)
                    self.n += 1
        """
    example_quiet = """
        import threading
        import time

        class Door:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def tick(self):
                time.sleep(0.05)
                with self._lock:
                    self.n += 1
        """

    def project_check(self, index) -> Iterable:
        lf = get_lockflow(index)
        seen = set()
        for ci, _name, fn in lf.all_methods:
            for ev in lf.events(fn, ci, ci.info):
                if not ev.held:
                    continue
                held = ev.held[-1]
                if ev.kind == "block":
                    key = (id(ev.node), ev.payload)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield self.finding(
                        ci.info,
                        ev.node,
                        f"{ev.payload} while holding '{held}': every "
                        "thread needing the lock stalls for the wait; "
                        "snapshot state under the lock and "
                        "wait/IO outside it",
                    )
                elif ev.kind == "call":
                    cfn, cinfo, label, cci = ev.payload
                    if cci is None:
                        cci = lf._owner_class(cfn, cinfo)
                    for op in lf.blocks(cfn, cci, cinfo):
                        chain = (
                            f"{label} -> {op.via}" if op.via else label
                        )
                        key = (id(ev.node), op.desc, chain)
                        if key in seen:
                            continue
                        seen.add(key)
                        yield self.finding(
                            ci.info,
                            ev.node,
                            f"call to {chain} performs {op.desc} while "
                            f"holding '{held}': every thread needing "
                            "the lock stalls for the wait; move the "
                            "blocking work outside the critical "
                            "section",
                        )
