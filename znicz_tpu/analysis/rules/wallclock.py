"""ZNC009: ``time.time()`` used for duration measurement.

The codebase standard for elapsed-time arithmetic is
``time.monotonic()`` / ``time.perf_counter()`` (``utils.profiling``'s
Stopwatch / StepTimer / PhaseTimer): ``time.time()`` is WALL clock, and
an NTP step mid-measurement corrupts the delta — negative latencies,
hour-long "epochs", silently wrong benchmark numbers.  ``time.time()``
is fine as a *timestamp* (log lines, filenames, absolute scheduling);
what this rule flags is wall-clock values entering SUBTRACTION — either
a direct ``time.time() - t0`` (or ``t1 - time.time()``), or a
subtraction whose both operands are names/attributes assigned from
``time.time()``.

Legitimate epoch-timestamp differences (e.g. comparing mtimes against
``time.time()``-derived deadlines across processes) are exempted inline
with ``# znicz-check: disable=ZNC009`` and a reason.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from znicz_tpu.analysis.rules import Rule, register


def _is_wall_call(info, node) -> bool:
    return (
        isinstance(node, ast.Call)
        and info.resolved(node.func) == "time.time"
    )


def _target_keys(info, tgt) -> List[str]:
    """Assignment-target names: ``t0`` for Name targets, the dotted
    path (``self._t0``) for attributes, flattened through tuples."""
    if isinstance(tgt, ast.Name):
        return [tgt.id]
    if isinstance(tgt, ast.Attribute):
        dotted = info.dotted(tgt)
        return [dotted] if dotted else []
    if isinstance(tgt, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in tgt.elts:
            out.extend(_target_keys(info, elt))
        return out
    return []


@register
class WallClockDurationRule(Rule):
    id = "ZNC009"
    severity = "warning"
    title = (
        "time.time() used for duration arithmetic (use time.monotonic()/"
        "time.perf_counter() or utils.profiling)"
    )

    example_fire = """
        import time

        def measure(work):
            t0 = time.time()
            work()
            return time.time() - t0
        """
    example_quiet = """
        import time

        def measure(work):
            t0 = time.monotonic()
            work()
            return time.monotonic() - t0
        """

    def check(self, info) -> Iterable:
        # pass 1: names (function-scoped) and attributes (module-wide —
        # self._t0 is typically set in __init__ and read elsewhere)
        # assigned from time.time()
        scoped_names = set()  # (id(enclosing function), name)
        wall_attrs = set()  # dotted attribute paths
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)):
                targets, value = [node.target], node.value
            else:
                continue
            if not _is_wall_call(info, value):
                continue
            scope = id(info.enclosing_function(node))
            for tgt in targets:
                for key in _target_keys(info, tgt):
                    if "." in key:
                        wall_attrs.add(key)
                    else:
                        scoped_names.add((scope, key))

        def wallish(node, scope) -> bool:
            if _is_wall_call(info, node):
                return True
            if isinstance(node, ast.Name):
                return (scope, node.id) in scoped_names
            if isinstance(node, ast.Attribute):
                return info.dotted(node) in wall_attrs
            return False

        # pass 2: subtractions consuming wall-clock values
        for node in ast.walk(info.tree):
            if not (
                isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Sub)
            ):
                continue
            scope = id(info.enclosing_function(node))
            direct = _is_wall_call(info, node.left) or _is_wall_call(
                info, node.right
            )
            derived = wallish(node.left, scope) and wallish(
                node.right, scope
            )
            if direct or derived:
                yield self.finding(
                    info,
                    node,
                    "wall-clock delta: time.time() jumps under NTP "
                    "steps; measure durations with time.monotonic()/"
                    "time.perf_counter() (utils.profiling Stopwatch/"
                    "StepTimer/PhaseTimer), or pragma-exempt a genuine "
                    "epoch-timestamp difference",
                )
