"""Rule registry.

Every rule is a class with a stable ``id`` (``ZNCnnn`` — never reuse a
retired number), a ``severity``, a one-line ``title`` (the catalog), and
``check(info) -> Iterable[Finding]``.  Registration is declarative via
the ``@register`` decorator; ``get_rules`` instantiates the active set.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

RULES: Dict[str, Type] = {}


def register(cls):
    if cls.id in RULES:  # never let two rules share an ID silently
        raise ValueError(f"duplicate rule id {cls.id}")
    RULES[cls.id] = cls
    return cls


class Rule:
    id = "ZNC000"
    severity = "error"
    title = "abstract rule"
    # project rules reason over the WHOLE ProjectIndex (call graph,
    # dataflow, cross-class lock model) instead of one module at a
    # time: their ``check`` is a no-op and ``project_check`` does the
    # work.  ``analyze_project`` runs both kinds; the per-module
    # ``analyze_source`` path only sees ``check``.
    project = False
    # ``--explain`` metadata: a minimal firing example and its
    # minimally-edited quiet twin.  The registry is the ONE source of
    # truth — the CLI prints these and the test suite executes them
    # (fire must fire, quiet must stay quiet).
    example_fire: str = ""
    example_quiet: str = ""
    # path the examples are analyzed under (scoped rules need a
    # serving-tier path) and sibling files some rules consult
    example_path: str = "pkg/mod.py"
    example_support_files: dict = {}

    def check(self, info):
        if self.project:
            return ()  # needs the project index; see project_check
        raise NotImplementedError

    def project_check(self, index):
        return ()

    def finding(self, info, node, message):
        return info.finding(self.id, self.severity, node, message)


def get_rules(
    select: Optional[Sequence[str]] = None,
    ignore: Optional[Sequence[str]] = None,
) -> List[Rule]:
    ids = sorted(RULES)
    if select:
        unknown = set(select) - set(ids)
        if unknown:
            raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
        ids = [i for i in ids if i in set(select)]
    if ignore:
        ids = [i for i in ids if i not in set(ignore)]
    return [RULES[i]() for i in ids]


# importing the modules performs registration
from znicz_tpu.analysis.rules import (  # noqa: E402,F401
    blocking,
    blocking_lock,
    donation,
    exceptions,
    host_effects,
    host_sync,
    lock_discipline,
    lock_order,
    metric_names,
    mutable_state,
    prng_keys,
    recompile_hazard,
    sharding_axes,
    thread_exceptions,
    traced_branch,
    wallclock,
)
