"""ZNC008: bare excepts and silently swallowed exceptions.

In ``parallel/`` and ``services/`` especially, a swallowed exception
turns a real failure (a dead collective, a half-written snapshot, a
broken status page) into silence — the reference stack's worst
operational trait, which this rebuild explicitly hardens against.  A
handler must do SOMETHING observable: log, re-raise, or return a
computed fallback.  ``except Exception: pass`` is allowed only with an
inline pragma stating why.
"""

from __future__ import annotations

import ast

from znicz_tpu.analysis.rules import Rule, register


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    """No call, raise, name binding, or value-returning fallback — the
    handler observes nothing.  ``return <fallback>`` counts as handling
    (a documented degraded result); a bare ``return`` does not."""
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(
                node,
                (
                    ast.Raise,
                    ast.Call,
                    ast.Assign,
                    ast.AugAssign,
                    ast.AnnAssign,
                    ast.FunctionDef,
                    ast.AsyncFunctionDef,
                    ast.ClassDef,
                    ast.Import,
                    ast.ImportFrom,
                ),
            ):
                return False
            if isinstance(node, ast.Return) and not (
                node.value is None
                or (
                    isinstance(node.value, ast.Constant)
                    and node.value.value is None
                )
            ):
                return False
    return True


@register
class SwallowedExceptionRule(Rule):
    id = "ZNC008"
    severity = "error"
    title = "bare except / silently swallowed exception"

    example_fire = """
        def probe():
            try:
                return 1
            except Exception:
                pass
        """
    example_quiet = """
        import logging

        logger = logging.getLogger(__name__)

        def probe():
            try:
                return 1
            except Exception:
                logger.exception("probe failed")
        """

    def check(self, info):
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield self.finding(
                    info,
                    node,
                    "bare 'except:' catches SystemExit/KeyboardInterrupt "
                    "too; catch a concrete exception type",
                )
            elif _handler_is_silent(node):
                type_src = (
                    info.dotted(node.type)
                    or getattr(node.type, "id", None)
                    or "…"
                )
                yield self.finding(
                    info,
                    node,
                    f"'except {type_src}' swallows the exception "
                    "silently; log it, re-raise, or exempt with a pragma "
                    "stating why silence is safe",
                )
