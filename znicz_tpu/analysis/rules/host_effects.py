"""ZNC002: host-side effects inside jitted/traced code.

``print``, ``time.time()``, file I/O, ``os``/``sys`` calls and raw
``numpy`` ops inside a traced function run once at TRACE time, not per
step — timing reads measure dispatch, prints fire once, and ``np.``
calls on traced arguments either crash or silently constant-fold.  The
sanctioned equivalents are ``jax.debug.print`` / ``jax.debug.callback``
and ``jnp`` ops.
"""

from __future__ import annotations

import ast

from znicz_tpu.analysis.rules import Rule, register

# builtins whose call inside traced code is a host effect
_BUILTIN_EFFECTS = {"print", "input", "breakpoint", "open", "exec", "eval"}
# module roots whose calls are host-side (after alias resolution)
_MODULE_EFFECTS = {
    "time",
    "os",
    "sys",
    "io",
    "shutil",
    "pathlib",
    "subprocess",
    "socket",
    "logging",
    "random",  # python's random, NOT jax.random
    "numpy",
}


@register
class HostEffectRule(Rule):
    id = "ZNC002"
    severity = "error"
    title = "host-side effect (print/time/io/np) inside jitted code"

    example_fire = """
        import time
        import jax

        @jax.jit
        def step(x):
            t = time.time()
            return x * t
        """
    example_quiet = """
        import time
        import jax

        @jax.jit
        def step(x):
            return x * 2.0

        def run(x):
            t = time.time()
            return step(x), t
        """

    def check(self, info):
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            if not info.traced.in_traced_code(node):
                continue
            # device->host syncs are method-spelled: x.block_until_ready()
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            ):
                yield self.finding(
                    info,
                    node,
                    "'.block_until_ready()' inside a jitted/traced "
                    "function is a host-side sync that cannot run "
                    "under the tracer",
                )
                continue
            resolved = info.resolved(node.func)
            if resolved is None:
                continue
            root = resolved.split(".")[0]
            # NOT device_put: inside jit it is a legitimate traceable
            # sharding/placement hint
            if resolved == "jax.device_get":
                yield self.finding(
                    info,
                    node,
                    "'jax.device_get' inside a jitted/traced function is "
                    "a host-side transfer that cannot run under the "
                    "tracer; return the value instead",
                )
            elif resolved in _BUILTIN_EFFECTS:
                yield self.finding(
                    info,
                    node,
                    f"'{resolved}' inside a jitted/traced function runs at "
                    "trace time only; use jax.debug.print/callback",
                )
            elif root in _MODULE_EFFECTS:
                hint = (
                    "use jnp ops on traced values"
                    if root == "numpy"
                    else "hoist it out of the traced function or use "
                    "jax.debug.callback"
                )
                yield self.finding(
                    info,
                    node,
                    f"host-side call '{resolved}' inside a jitted/traced "
                    f"function executes at trace time, not per step; {hint}",
                )
