"""ZNC015: lock-acquisition-order cycles (potential deadlocks).

The serving tier is a web of small locks — the front door's state
lock, the registry's roster lock, the affinity index's map lock, the
aggregator's snapshot lock — and threads cross between them: HTTP
workers into the router, the router into the registry and the
aggregator, heartbeat probes back into router hooks.  Two threads
acquiring two locks in OPPOSITE orders is the classic deadlock, and it
only manifests under load, never in unit tests.

This rule builds the project-wide **lock-order graph** from the shared
lock model (:mod:`znicz_tpu.analysis.lockmodel`): an edge ``A -> B``
exists when lock ``B`` is acquired while ``A`` is held — lexically
(``with self._a: ... with self._b:``) or transitively through calls
resolved via the PR 9 call graph (``self.m()``, typed cross-object
``self.attr.m()``, plain project functions).  Lock identity is
``module.Class.attr``: two instances of one class share the ordering
discipline, which is the granularity cycles care about.  Any cycle in
the graph is reported once, with the full path and each edge's
acquisition site; a self-edge on a non-reentrant lock (``with
self._lock:`` reaching a method that re-acquires ``self._lock``) is a
guaranteed SELF-deadlock and is reported too (RLocks are exempt).

Approximations (all toward silence): calls on untyped objects are
invisible, ``lock.acquire()`` call-form is not modeled, and aliased
locks are distinct identities.  A deliberate ordering the analysis
cannot see (e.g. a global total order enforced by sorted acquisition)
is exempted inline with ``# znicz-check: disable=ZNC015 -- <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Tuple

from znicz_tpu.analysis.lockmodel import LockAcq, get_lockflow
from znicz_tpu.analysis.rules import Rule, register


@register
class LockOrderRule(Rule):
    id = "ZNC015"
    severity = "warning"
    project = True
    title = (
        "lock-acquisition-order cycle across serving-tier locks "
        "(threads interleaving these acquisitions can deadlock)"
    )

    example_path = "services/mod.py"
    example_fire = """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._stats_lock = threading.Lock()

            def tick(self):
                with self._lock:
                    with self._stats_lock:
                        pass

            def stats(self):
                with self._stats_lock:
                    with self._lock:
                        pass
        """
    example_quiet = """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._stats_lock = threading.Lock()

            def tick(self):
                with self._lock:
                    with self._stats_lock:
                        pass

            def stats(self):
                with self._lock:
                    with self._stats_lock:
                        pass
        """

    def project_check(self, index) -> Iterable:
        lf = get_lockflow(index)
        # edge (A, B) -> representative (info, node, via) acquisition
        edges: Dict[Tuple[str, str], LockAcq] = {}
        for ci, _name, fn in lf.all_methods:
            for ev in lf.events(fn, ci, ci.info):
                if not ev.held:
                    continue
                acquired: List[LockAcq] = []
                if ev.kind == "acquire":
                    acquired = [
                        LockAcq(ev.payload, ev.node, ci.info, "")
                    ]
                elif ev.kind == "call":
                    cfn, cinfo, label, cci = ev.payload
                    if cci is None:
                        cci = lf._owner_class(cfn, cinfo)
                    acquired = [
                        LockAcq(a.lock, ev.node, ci.info,
                                label if not a.via
                                else f"{label} -> {a.via}")
                        for a in lf.acquires(cfn, cci, cinfo).values()
                    ]
                for acq in acquired:
                    for held in ev.held:
                        if acq.lock == held and lf.lock_kind(
                            held
                        ) == "rlock":
                            continue  # reentrant: re-acquisition is fine
                        edges.setdefault((held, acq.lock), acq)
        yield from self._report_cycles(edges)

    def _report_cycles(self, edges) -> Iterable:
        graph: Dict[str, List[str]] = {}
        for (a, b), _acq in edges.items():
            graph.setdefault(a, []).append(b)
        seen_cycles = set()
        for start in sorted(graph):
            for cycle in self._cycles_from(start, graph):
                key = self._canonical(cycle)
                if key in seen_cycles:
                    continue
                seen_cycles.add(key)
                yield self._cycle_finding(cycle, edges)

    @staticmethod
    def _cycles_from(start: str, graph) -> Iterable[List[str]]:
        """Simple cycles through ``start`` (tiny graphs: plain DFS)."""
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start:
                    yield path[:]
                elif nxt not in path and len(path) < 8:
                    stack.append((nxt, path + [nxt]))

    @staticmethod
    def _canonical(cycle: List[str]) -> Tuple[str, ...]:
        i = cycle.index(min(cycle))
        return tuple(cycle[i:] + cycle[:i])

    def _cycle_finding(self, cycle: List[str], edges):
        steps = []
        first_acq = None
        for i, lock in enumerate(cycle):
            nxt = cycle[(i + 1) % len(cycle)]
            acq = edges[(lock, nxt)]
            if first_acq is None:
                first_acq = acq
            site = f"{acq.info.path}:{getattr(acq.node, 'lineno', 0)}"
            via = f" via {acq.via}" if acq.via else ""
            steps.append(f"{lock} -> {nxt} (at {site}{via})")
        if len(cycle) == 1:
            message = (
                f"non-reentrant lock '{cycle[0]}' can be re-acquired "
                f"while already held ({steps[0]}): a guaranteed "
                "self-deadlock; use the lock-held-by-caller convention "
                "or an RLock"
            )
        else:
            message = (
                "lock-order cycle: "
                + "; ".join(steps)
                + " — threads interleaving these acquisitions can "
                "deadlock; pick one global order (or pragma-exempt "
                "with the ordering argument)"
            )
        return self.finding(first_acq.info, first_acq.node, message)
