"""ZNC007: device->host syncs and wall-clock reads inside host loops.

``jax.device_get`` / ``.block_until_ready()`` inside a per-minibatch
(or per-epoch) loop serializes dispatch against the device — the exact
round-trip cost the workflow's one-fetch-per-epoch accumulator design
exists to avoid (workflow.py's epoch contract).  ``time.time()`` inside
a loop is the same smell for timing: it measures dispatch, not compute,
and belongs in the shared ``utils.profiling`` helpers (StepTimer /
Stopwatch), which make the granularity explicit.

Once-per-epoch fetches that are part of the design are exempted inline
with ``# znicz-check: disable=ZNC007`` and a reason.
"""

from __future__ import annotations

import ast

from znicz_tpu.analysis.rules import Rule, register

_SYNC_CALLS = {"jax.device_get"}
_TIME_CALLS = {"time.time"}


def _in_loop(info, node) -> bool:
    """Inside a for/while body — without crossing a function boundary
    (a closure defined in a loop does not itself run per-iteration)."""
    cur = info.parents.get(node)
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return False
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return True
        cur = info.parents.get(cur)
    return False


@register
class HostSyncRule(Rule):
    id = "ZNC007"
    severity = "warning"
    title = "device_get/block_until_ready/time.time inside a host loop"

    example_fire = """
        import jax

        def losses(batches, acc):
            out = []
            for b in batches:
                out.append(jax.device_get(acc))
            return out
        """
    example_quiet = """
        import jax

        def losses(batches, acc):
            for b in batches:
                pass
            return jax.device_get(acc)
        """

    def check(self, info):
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            if info.traced.in_traced_code(node):
                continue  # traced code: ZNC002's jurisdiction
            if not _in_loop(info, node):
                continue
            resolved = info.resolved(node.func) or ""
            if resolved in _SYNC_CALLS:
                yield self.finding(
                    info,
                    node,
                    f"'{resolved}' inside a loop forces a device->host "
                    "round trip per iteration; accumulate on device and "
                    "fetch once (or exempt a per-epoch fetch explicitly)",
                )
            elif resolved in _TIME_CALLS:
                yield self.finding(
                    info,
                    node,
                    "'time.time()' inside a loop: use the shared "
                    "utils.profiling StepTimer/Stopwatch so timing "
                    "granularity is explicit and consistent",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            ):
                yield self.finding(
                    info,
                    node,
                    "'.block_until_ready()' inside a loop serializes "
                    "dispatch against the device every iteration",
                )
