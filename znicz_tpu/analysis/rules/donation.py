"""ZNC005: jitted train-step-shaped callables without buffer donation.

A jitted function that takes and returns a train state doubles the
state's HBM footprint unless the input buffers are donated
(``donate_argnums``) — on a memory-bound TPU run that is the difference
between fitting and OOM, and XLA's in-place update path is also faster.
The heuristic: a ``jax.jit``/``pjit`` application whose wrapped function
has a non-static parameter with a state-suggesting name (``state``,
``train_state``, ``opt_state``) and no donation kwarg.
"""

from __future__ import annotations

from znicz_tpu.analysis.rules import Rule, register
from znicz_tpu.analysis.context import _param_names

_STATE_NAMES = {
    "state",
    "train_state",
    "opt_state",
    "tstate",
    "optimizer_state",
}


@register
class DonationRule(Rule):
    id = "ZNC005"
    severity = "warning"
    title = "jitted train-state function without donate_argnums"

    def check(self, info):
        for jc in info.traced.jit_calls:
            if jc.fn is None or jc.has_donation():
                continue
            static = jc.static_names()
            hits = [
                p
                for p in _param_names(jc.fn)
                if p in _STATE_NAMES and p not in static
            ]
            if hits:
                yield self.finding(
                    info,
                    jc.node,
                    f"jit of '{jc.fn.name}' takes state-shaped "
                    f"argument(s) {', '.join(hits)} but declares no "
                    "donate_argnums — the old state's buffers stay live "
                    "and double the HBM footprint",
                )
