"""ZNC005: jitted train-step-shaped callables without buffer donation.

A jitted function that takes and returns a train state doubles the
state's HBM footprint unless the input buffers are donated
(``donate_argnums``) — on a memory-bound TPU run that is the difference
between fitting and OOM, and XLA's in-place update path is also faster.
The heuristic: a ``jax.jit``/``pjit`` application whose wrapped function
has a non-static parameter that is state-shaped — a state-suggesting
NAME (``state``, ``train_state``, ``opt_state``) or a ``TrainState``
type ANNOTATION (plain, dotted, wrapped as ``Optional[TrainState]``, or
a string forward reference), so renaming the parameter does not dodge
the check — and no donation kwarg.
"""

from __future__ import annotations

import ast
import re

from znicz_tpu.analysis.rules import Rule, register

_STATE_NAMES = {
    "state",
    "train_state",
    "opt_state",
    "tstate",
    "optimizer_state",
}
# type names that mark a parameter as train state regardless of its name
_STATE_TYPES = {"TrainState"}


def _annotation_is_state(ann: ast.AST) -> bool:
    """Does the annotation mention a state type anywhere — ``TrainState``,
    ``train_state.TrainState``, ``Optional[TrainState]``, or the string
    form ``"TrainState"``?"""
    for node in ast.walk(ann):
        if isinstance(node, ast.Name) and node.id in _STATE_TYPES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _STATE_TYPES:
            return True
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            # word-boundary match: "Optional[TrainState]" fires,
            # "TrainStateless" (a different type) does not
            if any(
                re.search(rf"\b{t}\b", node.value) for t in _STATE_TYPES
            ):
                return True
    return False


def _state_params(fn) -> list:
    """Parameter names that look state-shaped by NAME or by ANNOTATION
    (lambdas carry no annotations; the name path still applies)."""
    args = fn.args
    out = []
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        by_name = a.arg in _STATE_NAMES
        by_type = a.annotation is not None and _annotation_is_state(
            a.annotation
        )
        if by_name or by_type:
            out.append(a.arg)
    return out


@register
class DonationRule(Rule):
    id = "ZNC005"
    severity = "warning"
    title = "jitted train-state function without donate_argnums"

    example_fire = """
        import jax

        def step(state, batch):
            return state

        fast = jax.jit(step)
        """
    example_quiet = """
        import jax

        def step(state, batch):
            return state

        fast = jax.jit(step, donate_argnums=(0,))
        """

    def check(self, info):
        for jc in info.traced.jit_calls:
            if jc.fn is None or jc.has_donation():
                continue
            static = jc.static_names()
            hits = [
                p for p in _state_params(jc.fn) if p not in static
            ]
            if hits:
                name = getattr(jc.fn, "name", "<lambda>")
                yield self.finding(
                    info,
                    jc.node,
                    f"jit of '{name}' takes state-shaped "
                    f"argument(s) {', '.join(hits)} (by name or "
                    "TrainState annotation) but declares no "
                    "donate_argnums — the old state's buffers stay live "
                    "and double the HBM footprint",
                )
