"""ZNC006: mutable defaults and mutable module state near jitted code.

Mutable default arguments are the classic Python shared-state bug; in a
jax codebase they are worse, because a default that leaks into a jitted
call participates in tracing and caching.  Module-level mutable
literals captured by a traced closure are baked in as compile-time
constants at FIRST trace — later mutation silently does nothing to the
compiled program.  ``global`` inside a traced function can only be a
host-side effect at trace time.
"""

from __future__ import annotations

import ast
from typing import Dict

from znicz_tpu.analysis.context import scope_local_names
from znicz_tpu.analysis.rules import Rule, register

_MUTABLE_LITERALS = (
    ast.List,
    ast.Dict,
    ast.Set,
    ast.ListComp,
    ast.DictComp,
    ast.SetComp,
)
_MUTABLE_CALLS = {"list", "dict", "set", "bytearray", "defaultdict"}


def _is_mutable_expr(node: ast.AST) -> bool:
    if isinstance(node, _MUTABLE_LITERALS):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in _MUTABLE_CALLS
    return False


@register
class MutableStateRule(Rule):
    id = "ZNC006"
    severity = "warning"
    title = "mutable default arg / mutable module state in jitted closure"

    example_fire = """
        def collect(x, seen=[]):
            seen.append(x)
            return seen
        """
    example_quiet = """
        def collect(x, seen=None):
            if seen is None:
                seen = []
            seen.append(x)
            return seen
        """

    def check(self, info):
        # (a) mutable default arguments, anywhere
        for fn in ast.walk(info.tree):
            if not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            defaults = list(fn.args.defaults) + [
                d for d in fn.args.kw_defaults if d is not None
            ]
            for d in defaults:
                if _is_mutable_expr(d):
                    name = getattr(fn, "name", "<lambda>")
                    yield self.finding(
                        info,
                        d,
                        f"mutable default argument in '{name}' is shared "
                        "across calls; default to None and create inside",
                    )
        # module-level names bound to mutable literals
        module_mutables: Dict[str, ast.AST] = {}
        for stmt in info.tree.body:
            if isinstance(stmt, ast.Assign) and _is_mutable_expr(
                stmt.value
            ):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        module_mutables[t.id] = stmt
        # (b) traced closures capturing module-level mutables; (c) global
        for node in ast.walk(info.tree):
            if not info.traced.in_traced_code(node):
                continue
            if isinstance(node, ast.Global):
                yield self.finding(
                    info,
                    node,
                    "'global' inside a jitted/traced function mutates "
                    "host state at trace time only",
                )
            elif (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in module_mutables
            ):
                fn = info.enclosing_function(node)
                local_names = set()
                while fn is not None:
                    local_names |= scope_local_names(fn)
                    fn = info.enclosing_function(fn)
                if node.id in local_names:
                    continue  # shadowed by a parameter or local binding
                yield self.finding(
                    info,
                    node,
                    f"module-level mutable '{node.id}' captured by a "
                    "jitted/traced function is frozen at first trace; "
                    "pass it as an argument or make it immutable",
                )
