"""ZNC013: background threads whose death is not a typed event.

The PR 6 serving contract (docs/SERVING.md): **a thread death must be
a typed event** — the front door's engine thread converts crashes into
typed ``error`` completions plus a rebuild, the registry's heartbeat
loop logs and keeps sweeping.  A ``threading.Thread(target=...)``
whose target body can raise OUTSIDE a try/except that handles the
exception dies with nothing but the interpreter's default stderr
traceback: the watchdog never fires, the queue quietly stops draining,
and the first symptom is a hung client.

Scope: ``services/``, ``cluster/`` and ``observability/`` modules.
For every ``threading.Thread(...)`` call whose ``target=`` resolves
statically — ``self._loop`` (a method of the enclosing class), a
module-level or local ``def``, a ``lambda``, or a
``partial(fn, ...)`` of one — the rule scans the target body for a
call (or ``raise``) that is not protected by a ``try`` whose handler
catches broadly (``Exception`` / ``BaseException`` / bare) AND does
something with it (contains at least one call — ``logger.exception``,
a typed-event hook like ``self._engine_failure(exc)``; a silent
``pass`` handler protects nothing, and ZNC008 flags it separately).

Benign waits are whitelisted so the canonical loop shape stays quiet::

    while not self._stop.wait(timeout=self.interval_s):   # safe
        try:
            self._sweep()                                  # guarded
        except Exception:
            logger.warning("sweep failed", exc_info=True)

A target that genuinely cannot raise (every callee guards internally)
is exempted at the ``Thread(...)`` line with
``# znicz-check: disable=ZNC013 -- <reason>``.  Targets the analyzer
cannot resolve (an imported callable, another object's method) are
skipped, not guessed at.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from znicz_tpu.analysis.rules import Rule, register

# Event/Condition/loop plumbing that does not raise in practice
_SAFE_ATTR_CALLS = {"wait", "is_set", "set", "clear", "is_alive"}
# logging methods (logger.warning(...), logging.exception(...))
_LOG_METHODS = {
    "debug",
    "info",
    "warning",
    "error",
    "exception",
    "critical",
    "log",
}
_SAFE_RESOLVED = {
    "time.sleep",
    "time.monotonic",
    "time.perf_counter",
    "time.time",
    "len",
    "int",
    "float",
    "str",
    "bool",
    "round",
    "min",
    "max",
}
_BROAD_EXCEPTIONS = {"Exception", "BaseException"}


def _handler_is_broad_and_typed(handler: ast.ExceptHandler) -> bool:
    """A handler that catches everything and DOES something: logging,
    a typed-event callback — anything but swallowing silently."""
    if handler.type is not None:
        t = handler.type
        tails: List[str] = []
        for e in t.elts if isinstance(t, ast.Tuple) else [t]:
            if isinstance(e, ast.Attribute):
                tails.append(e.attr)
            elif isinstance(e, ast.Name):
                tails.append(e.id)
        if not any(name in _BROAD_EXCEPTIONS for name in tails):
            return False
    # the handler must DO something that isn't itself a (re-)raise: a
    # `raise RuntimeError(exc)` handler still kills the thread, so its
    # exception-constructor call does not make it a sink
    in_raise = set()
    for r in ast.walk(handler):
        if isinstance(r, ast.Raise):
            in_raise.update(id(n) for n in ast.walk(r))
    return any(
        isinstance(n, ast.Call) and id(n) not in in_raise
        for n in ast.walk(handler)
    )


class _BodyScan:
    """Find the first call/raise a thread target can die on."""

    def __init__(self, info):
        self.info = info
        self.first: Optional[Tuple[int, str]] = None

    def _risky_call(self, call: ast.Call) -> Optional[str]:
        resolved = self.info.resolved(call.func)
        if resolved in _SAFE_RESOLVED:
            return None
        if resolved is not None and resolved.split(".")[0] == "logging":
            return None
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in _SAFE_ATTR_CALLS | _LOG_METHODS:
                return None
            return f"self-or-object call '.{call.func.attr}()'"
        if resolved is not None:
            return f"call '{resolved}()'"
        return "call"

    def _note(self, node: ast.AST, what: str) -> None:
        if self.first is None:
            self.first = (getattr(node, "lineno", 0), what)

    def _scan_expr(self, node: ast.AST) -> None:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                what = self._risky_call(n)
                if what is not None:
                    self._note(n, what)

    def scan(self, stmts: List[ast.stmt], protected: bool) -> None:
        for s in stmts:
            if isinstance(
                s,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue  # nested defs run elsewhere
            if isinstance(s, ast.Try):
                covers = protected or any(
                    _handler_is_broad_and_typed(h) for h in s.handlers
                )
                self.scan(s.body, covers)
                for h in s.handlers:
                    # a broad, non-silent handler IS the typed-event
                    # sink — the rule does not demand infinite regress
                    # into what the sink itself calls
                    self.scan(
                        h.body,
                        protected or _handler_is_broad_and_typed(h),
                    )
                self.scan(s.orelse, covers)
                self.scan(s.finalbody, protected)
                continue
            if isinstance(s, (ast.While, ast.If)):
                if not protected:
                    self._scan_expr(s.test)
                self.scan(s.body, protected)
                self.scan(s.orelse, protected)
                continue
            if isinstance(s, (ast.For, ast.AsyncFor)):
                if not protected:
                    self._scan_expr(s.iter)
                self.scan(s.body, protected)
                self.scan(s.orelse, protected)
                continue
            if isinstance(s, (ast.With, ast.AsyncWith)):
                if not protected:
                    for item in s.items:
                        self._scan_expr(item.context_expr)
                self.scan(s.body, protected)
                continue
            if protected:
                continue
            if isinstance(s, ast.Raise):
                self._note(s, "raise")
                continue
            self._scan_expr(s)


@register
class ThreadExceptionSinkRule(Rule):
    id = "ZNC013"
    severity = "warning"
    title = (
        "background-thread target can raise outside a handled "
        "try/except (a thread death must be a typed event)"
    )

    example_path = "services/mod.py"
    example_fire = """
        import threading

        class Pusher:
            def start(self, push):
                self._push = push
                t = threading.Thread(target=self._loop)
                t.start()

            def _loop(self):
                self._push()
        """
    example_quiet = """
        import logging
        import threading

        logger = logging.getLogger(__name__)

        class Pusher:
            def start(self, push):
                self._push = push
                t = threading.Thread(target=self._loop)
                t.start()

            def _loop(self):
                try:
                    self._push()
                except Exception:
                    logger.exception("push failed; thread exiting")
        """

    def _in_scope(self, info) -> bool:
        # ONE owner of the serving-tier scope (lockmodel.SERVING_SCOPES)
        # — a new serving package widens every concurrency rule at once
        from znicz_tpu.analysis.lockmodel import in_serving_scope

        return in_serving_scope(info)

    def _resolve_target(self, info, thread_call: ast.Call, expr):
        """The target's FunctionDef/Lambda, or None when not statically
        resolvable.  Handles ``partial(fn, ...)``."""
        if (
            isinstance(expr, ast.Call)
            and (info.resolved(expr.func) or "").rpartition(".")[2]
            == "partial"
            and expr.args
        ):
            expr = expr.args[0]
        if isinstance(expr, ast.Lambda):
            return expr
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            cur = info.parents.get(thread_call)
            while cur is not None and not isinstance(cur, ast.ClassDef):
                cur = info.parents.get(cur)
            if cur is None:
                return None
            for node in cur.body:
                if (
                    isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)
                    )
                    and node.name == expr.attr
                ):
                    return node
            return None
        if isinstance(expr, ast.Name):
            for fn, _bound in info.traced._resolve_local(
                expr, thread_call
            ):
                return fn
        return None

    def check(self, info) -> Iterable:
        if not self._in_scope(info):
            return
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            if info.resolved(node.func) != "threading.Thread":
                continue
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None:
                continue
            fn = self._resolve_target(info, node, target)
            if fn is None:
                continue
            scan = _BodyScan(info)
            if isinstance(fn, ast.Lambda):
                scan._scan_expr(fn.body)
                name = "<lambda>"
            else:
                scan.scan(fn.body, protected=False)
                name = fn.name
            if scan.first is None:
                continue
            line, what = scan.first
            yield self.finding(
                info,
                node,
                f"thread target '{name}' can die on an unhandled "
                f"exception ({what} at line {line} runs outside a "
                "try/except that catches Exception and handles it); "
                "wrap the risky work so a thread death becomes a "
                "logged/typed event, or pragma-exempt with a reason",
            )
