"""ZNC004: PRNG key hygiene — hard-coded seeds and key reuse.

The sanctioned key source is ``znicz_tpu.core.prng`` (named generator
registry): it makes every stream reproducible, decorrelated, and
snapshot-resumable.  ``jax.random.key(0)`` scattered through the code
silently correlates streams and breaks the exact-resume contract.

Reuse: passing the SAME key object to two consuming ``jax.random``
samplers yields identical draws — the classic silent-correlation bug.
Detection is conservative: a name is only flagged when it is consumed
by two or more sampler calls within one function and never reassigned
between (names that are ever re-bound in the function are skipped).
"""

from __future__ import annotations

import ast
from typing import Dict, List

from znicz_tpu.analysis.rules import Rule, register

# jax.random callables that DERIVE rather than consume (not reuse sinks)
_DERIVERS = {
    "split",
    "fold_in",
    "key",
    "PRNGKey",
    "key_data",
    "wrap_key_data",
    "key_impl",
    "clone",
}
_KEY_MAKERS = {"jax.random.key", "jax.random.PRNGKey"}
_SANCTIONED_PATH = "core/prng.py"


def _jax_random_call(info, node: ast.Call):
    resolved = info.resolved(node.func) or ""
    if resolved.startswith("jax.random."):
        return resolved[len("jax.random."):]
    return None


def _walk_own_scope(fn):
    """Descendants of ``fn`` WITHOUT entering nested function scopes —
    ``ast.walk`` would yield their bodies too, conflating the key
    namespaces of sibling closures."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue  # its own pass covers it
        stack.extend(ast.iter_child_nodes(node))


def _branch_arms(info, node):
    """{branching-node-id: arm} for every If/Try on ``node``'s ancestor
    chain, where arm identifies which mutually exclusive list (if-body
    vs orelse, try-body vs handlers) contains the chain."""
    arms = {}
    cur = node
    parent = info.parents.get(cur)
    while parent is not None:
        if isinstance(parent, ast.If):
            if any(cur is stmt for stmt in parent.body):
                arms[id(parent)] = "body"
            elif any(cur is stmt for stmt in parent.orelse):
                arms[id(parent)] = "orelse"
        elif isinstance(parent, ast.Try):
            if any(cur is stmt for stmt in parent.body):
                arms[id(parent)] = "body"
            elif any(cur is h for h in parent.handlers):
                arms[id(parent)] = "handlers"
        cur, parent = parent, info.parents.get(parent)
    return arms


def _mutually_exclusive(info, a, b) -> bool:
    """True when ``a`` and ``b`` sit in disjoint arms of a shared
    If/Try — at most one of them executes, so it is not key reuse."""
    arms_a = _branch_arms(info, a)
    arms_b = _branch_arms(info, b)
    return any(
        key in arms_b and arms_b[key] != arm
        for key, arm in arms_a.items()
    )


@register
class PrngKeyRule(Rule):
    id = "ZNC004"
    severity = "warning"
    title = "hard-coded jax.random key / key reuse outside core/prng"

    example_fire = """
        import jax

        def sample(shape):
            key = jax.random.PRNGKey(0)
            return jax.random.normal(key, shape)
        """
    example_quiet = """
        import jax

        def sample(key, shape):
            return jax.random.normal(key, shape)
        """

    def check(self, info):
        sanctioned = info.path.replace("\\", "/").endswith(
            _SANCTIONED_PATH
        )
        # (a) hard-coded key construction
        if not sanctioned:
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Call):
                    continue
                resolved = info.resolved(node.func) or ""
                if resolved in _KEY_MAKERS and any(
                    isinstance(a, ast.Constant)
                    and isinstance(a.value, int)
                    for a in (
                        list(node.args)
                        + [kw.value for kw in node.keywords if kw.arg]
                    )
                ):
                    yield self.finding(
                        info,
                        node,
                        f"hard-coded '{resolved.rsplit('.', 1)[-1]}' seed "
                        "outside core/prng.py; derive keys from the named "
                        "generator registry (core.prng.get(name).key()) so "
                        "streams stay decorrelated and resumable",
                    )
        # (b) same key consumed by >= 2 samplers with no re-binding of
        # the name between the consumptions (line-position approximation:
        # an assignment strictly between two uses resets the chain).
        # Every name scope gets a pass: module level, functions, lambdas.
        scopes = [info.tree] + [
            n
            for n in ast.walk(info.tree)
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            )
        ]
        for fn in scopes:
            assigned: Dict[str, List[int]] = {}
            consumed: Dict[str, List[ast.Call]] = {}
            for node in _walk_own_scope(fn):
                if isinstance(
                    node, (ast.Assign, ast.AugAssign, ast.AnnAssign)
                ):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Name):
                                assigned.setdefault(sub.id, []).append(
                                    node.lineno
                                )
                elif isinstance(node, (ast.For, ast.comprehension)):
                    for sub in ast.walk(node.target):
                        if isinstance(sub, ast.Name):
                            assigned.setdefault(sub.id, []).append(
                                getattr(node, "lineno", 0)
                            )
                if not isinstance(node, ast.Call):
                    continue
                sampler = _jax_random_call(info, node)
                if sampler is None or sampler in _DERIVERS:
                    continue
                key_arg = (
                    node.args[0]
                    if node.args
                    else next(
                        (
                            kw.value
                            for kw in node.keywords
                            if kw.arg == "key"
                        ),
                        None,
                    )
                )
                if isinstance(key_arg, ast.Name):
                    consumed.setdefault(key_arg.id, []).append(node)
            for name, sites in consumed.items():
                if len(sites) < 2:
                    continue
                sites.sort(key=lambda s: s.lineno)
                lines = assigned.get(name, [])
                for prev, site in zip(sites, sites[1:]):
                    if any(
                        prev.lineno < a <= site.lineno for a in lines
                    ):
                        continue  # re-bound between the two consumptions
                    if _mutually_exclusive(info, prev, site):
                        continue  # disjoint if/try arms: only one runs
                    yield self.finding(
                        info,
                        site,
                        f"PRNG key '{name}' is consumed by multiple "
                        "jax.random samplers in this function — identical "
                        "draws; split the key (jax.random.split) per "
                        "consumer",
                    )
