"""``python -m znicz_tpu.analysis`` — the znicz-check CLI.

Exit codes: 0 = clean against the baseline, 1 = new findings (or
syntax errors), 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from znicz_tpu.analysis.engine import (
    analyze_paths,
    load_baseline,
    new_findings,
    stale_baseline_entries,
    write_baseline,
)
from znicz_tpu.analysis.rules import RULES, get_rules

# Anchor defaults to the repo root (the package's parent), NOT the cwd:
# fingerprint paths and the baseline location must agree no matter where
# the CLI is invoked from.
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(
    REPO_ROOT, "tools", "znicz_check_baseline.json"
)


def _split_ids(value):
    return [v.strip() for v in value.split(",") if v.strip()]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="znicz-check",
        description=(
            "AST-based JAX-hygiene & sharding-consistency analyzer "
            "for the znicz_tpu package"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=None,
        help="files/directories to analyze (default: the znicz_tpu "
        "package, wherever it is installed)",
    )
    parser.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help=f"suppression baseline file (default: {DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="grandfather all current findings into the baseline file",
    )
    parser.add_argument(
        "--select", type=_split_ids, help="only run these rule IDs"
    )
    parser.add_argument(
        "--ignore", type=_split_ids, help="skip these rule IDs"
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--root",
        default=REPO_ROOT,
        help="directory finding paths are reported relative to "
        "(default: the repo root; must match the baseline's)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id in sorted(RULES):
            cls = RULES[rule_id]
            print(f"{rule_id} [{cls.severity}] {cls.title}")
        return 0

    default_target = os.path.join(REPO_ROOT, "znicz_tpu")
    paths = args.paths or [default_target]
    # "full run" = every rule over the whole package — the only state a
    # baseline regen (or a stale-entry verdict) is meaningful against
    full_run = (
        not (args.select or args.ignore)
        and {os.path.abspath(p) for p in paths}
        == {os.path.abspath(default_target)}
    )

    if args.write_baseline and not full_run:
        # a partial regen (rule or path subset) would silently erase
        # every other rule's/file's grandfathered entries
        parser.error(
            "--write-baseline requires a full run (all rules, default "
            "paths); drop --select/--ignore and positional paths"
        )

    try:
        rules = get_rules(args.select, args.ignore)
    except ValueError as exc:
        parser.error(str(exc))

    try:
        findings = analyze_paths(paths, root=args.root, rules=rules)
    except FileNotFoundError as exc:
        parser.error(str(exc))

    if args.write_baseline:
        write_baseline(findings, args.baseline)
        print(
            f"wrote {len(findings)} finding(s) to {args.baseline}",
            file=sys.stderr,
        )
        return 0

    baseline = (
        load_baseline(args.baseline) if not args.no_baseline else None
    )
    report = (
        findings if baseline is None else new_findings(findings, baseline)
    )

    if args.format == "json":
        print(
            json.dumps(
                [f.__dict__ for f in report],
                indent=2,
            )
        )
    else:
        for f in report:
            print(f.format())
        suppressed = len(findings) - len(report)
        summary = f"{len(report)} new finding(s)"
        if baseline is not None:
            summary += f", {suppressed} baselined"
            # on a rule/path subset most baselined entries didn't get a
            # chance to fire, so "stale" would be meaningless (and the
            # recommended regen destructive)
            stale = (
                stale_baseline_entries(findings, baseline)
                if full_run
                else {}
            )
            if stale:
                summary += (
                    f"; {sum(stale.values())} baseline entr(ies) no "
                    "longer fire — regenerate with --write-baseline"
                )
        print(summary, file=sys.stderr)

    return 1 if report else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `znicz-check | head` closing the pipe early is not a failure
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
