"""``python -m znicz_tpu.analysis`` — the znicz-check CLI.

Runs the PROJECT-WIDE analysis (:mod:`znicz_tpu.analysis.project`):
one index over every analyzed module, so a ``jax.jit`` applied in a
different module than the function definition still marks it traced,
and helpers reachable only from traced callers are reported at the
traced entry point.

Exit codes: 0 = clean against the baseline, 1 = new findings (or
syntax errors), 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import time
from typing import Optional

import textwrap

from znicz_tpu.analysis.cache import (
    DEFAULT_CACHE_RELPATH,
    analyze_project_cached,
)
from znicz_tpu.analysis.engine import (
    load_baseline,
    new_findings,
    stale_baseline_entries,
    stale_baseline_meta,
    write_baseline,
)
from znicz_tpu.analysis.project import analyze_project
from znicz_tpu.analysis.rules import RULES, get_rules

# Anchor defaults to the repo root (the package's parent), NOT the cwd:
# fingerprint paths and the baseline location must agree no matter where
# the CLI is invoked from.
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(
    REPO_ROOT, "tools", "znicz_check_baseline.json"
)

SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _split_ids(value):
    return [v.strip() for v in value.split(",") if v.strip()]


def explain_rule(rule_id: str) -> Optional[str]:
    """The ``--explain`` text for one rule, entirely from registry
    metadata (class attributes + the rule module's docstring) — no
    second source of truth to drift.  None for an unknown id."""
    cls = RULES.get(rule_id)
    if cls is None:
        return None
    mod = sys.modules.get(cls.__module__)
    doc = (getattr(mod, "__doc__", "") or "").strip()
    lines = [
        f"{rule_id} [{cls.severity}] {cls.title}",
        "scope: " + ("project-wide" if cls.project else "per-module"),
        "",
        doc,
    ]
    if cls.example_fire.strip():
        lines += [
            "",
            f"FIRES ({cls.example_path}):",
            textwrap.indent(
                textwrap.dedent(cls.example_fire).strip(), "    "
            ),
        ]
        for path, src in sorted(cls.example_support_files.items()):
            lines += [
                f"  with sibling {path}:",
                textwrap.indent(textwrap.dedent(src).strip(), "    "),
            ]
    if cls.example_quiet.strip():
        lines += [
            "",
            "QUIET (minimally edited twin):",
            textwrap.indent(
                textwrap.dedent(cls.example_quiet).strip(), "    "
            ),
        ]
    return "\n".join(lines)


def _changed_files(ref: str, root: str):
    """ROOT-relative posix paths of ``.py`` files touched vs ``ref``:
    committed + working-tree changes (``git diff``) plus untracked
    files — what a pre-push hook or an editor wants annotated.  Git
    prints ``diff`` paths relative to the repo TOP LEVEL and
    ``ls-files`` paths relative to the cwd, while finding paths are
    relative to ``--root`` — everything is rebased onto ``root`` here
    (files outside it are dropped), or the filter would silently never
    match.  Raises ``RuntimeError`` with git's own message when the
    ref is bogus."""
    root = os.path.abspath(root)
    proc = subprocess.run(
        ["git", "-C", root, "rev-parse", "--show-toplevel"],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            proc.stderr.strip() or f"{root} is not inside a git repo"
        )
    toplevel = proc.stdout.strip()
    out = set()
    for base, args in (
        (
            toplevel,  # git diff paths are always toplevel-relative
            ["git", "-C", root, "diff", "--name-only", ref, "--", "*.py"],
        ),
        (
            root,  # ls-files paths are cwd-relative (-C root)
            [
                "git", "-C", root, "ls-files", "--others",
                "--exclude-standard", "--", "*.py",
            ],
        ),
    ):
        proc = subprocess.run(args, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                proc.stderr.strip() or f"git failed: {' '.join(args)}"
            )
        for line in proc.stdout.splitlines():
            if not line.strip():
                continue
            rel = os.path.relpath(
                os.path.join(base, line.strip()), root
            ).replace(os.sep, "/")
            if not rel.startswith("../"):
                out.add(rel)
    return out


def sarif_report(findings, root: str) -> dict:
    """SARIF 2.1.0 document for CI/editor inline annotation.  Rule
    metadata comes from the registry; levels map straight off the
    severity; ``partialFingerprints`` carries the baseline fingerprint
    so a SARIF consumer's dedup agrees with ours; ``SRCROOT`` resolves
    to the analysis root so base-honoring viewers open the real
    files."""
    seen_rules = sorted({f.rule for f in findings})
    rules_meta = []
    for rid in seen_rules:
        cls = RULES.get(rid)
        rules_meta.append(
            {
                "id": rid,
                "shortDescription": {
                    "text": (
                        cls.title
                        if cls is not None
                        else "unparseable module"
                    )
                },
                "defaultConfiguration": {
                    "level": (
                        cls.severity if cls is not None else "error"
                    )
                },
            }
        )
    results = [
        {
            "ruleId": f.rule,
            "level": f.severity,
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {
                            "startLine": max(f.line, 1),
                            "startColumn": max(f.col, 1),
                        },
                    },
                    "logicalLocations": [
                        {"fullyQualifiedName": f.symbol}
                    ],
                }
            ],
            "partialFingerprints": {"zniczCheck/v1": f.fingerprint},
        }
        for f in findings
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "znicz-check",
                        "informationUri": (
                            "docs/STATIC_ANALYSIS.md"
                        ),
                        "rules": rules_meta,
                    }
                },
                "originalUriBaseIds": {
                    "SRCROOT": {
                        "uri": pathlib.Path(
                            os.path.abspath(root)
                        ).as_uri()
                        + "/"
                    }
                },
                "results": results,
            }
        ],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="znicz-check",
        description=(
            "Project-wide AST-based JAX-hygiene, sharding-consistency "
            "and serving-tier thread-safety analyzer for the znicz_tpu "
            "package"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=None,
        help="files/directories to analyze (default: the znicz_tpu "
        "package, wherever it is installed)",
    )
    parser.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help=f"suppression baseline file (default: {DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="grandfather all current findings into the baseline file",
    )
    parser.add_argument(
        "--select", type=_split_ids, help="only run these rule IDs"
    )
    parser.add_argument(
        "--ignore", type=_split_ids, help="skip these rule IDs"
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text"
    )
    parser.add_argument(
        "--changed",
        metavar="REF",
        help="report findings only for files touched vs this git ref "
        "(the project index is still built whole-repo, so "
        "cross-module results stay correct)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--explain",
        metavar="RULE_ID",
        help="print one rule's catalog entry plus a firing example "
        "and its quiet twin (from registry metadata), then exit",
    )
    parser.add_argument(
        "--no-cache",
        action="store_true",
        help="skip the incremental analysis cache (always re-analyze)",
    )
    parser.add_argument(
        "--cache",
        metavar="PATH",
        default=None,
        help="incremental cache file (default: "
        f"<root>/{DEFAULT_CACHE_RELPATH}; content-hash keyed, safe "
        "to delete, never commit)",
    )
    parser.add_argument(
        "--root",
        default=REPO_ROOT,
        help="directory finding paths are reported relative to "
        "(default: the repo root; must match the baseline's)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id in sorted(RULES):
            cls = RULES[rule_id]
            print(f"{rule_id} [{cls.severity}] {cls.title}")
        return 0

    if args.explain:
        text = explain_rule(args.explain.strip())
        if text is None:
            parser.error(
                f"unknown rule id: {args.explain} (see --list-rules)"
            )
        print(text)
        return 0

    default_target = os.path.join(REPO_ROOT, "znicz_tpu")
    paths = args.paths or [default_target]
    # "full run" = every rule over the whole package, unfiltered — the
    # only state a baseline regen (or a stale-entry verdict) is
    # meaningful against
    full_run = (
        not (args.select or args.ignore or args.changed)
        and {os.path.abspath(p) for p in paths}
        == {os.path.abspath(default_target)}
    )

    if args.write_baseline and not full_run:
        # a partial regen (rule, path or changed-file subset) would
        # silently erase every other rule's/file's grandfathered entries
        parser.error(
            "--write-baseline requires a full run (all rules, default "
            "paths); drop --select/--ignore/--changed and positional "
            "paths"
        )

    try:
        rules = get_rules(args.select, args.ignore)
    except ValueError as exc:
        parser.error(str(exc))

    report_paths = None
    if args.changed is not None:
        try:
            report_paths = _changed_files(args.changed, args.root)
        except (RuntimeError, OSError) as exc:
            parser.error(f"--changed {args.changed}: {exc}")

    # the cache is only engaged for the FULL rule set: a --select/
    # --ignore subset would thrash one shared cache between two
    # incompatible finding universes
    use_cache = not args.no_cache and not (args.select or args.ignore)
    cache_stats = None
    t0 = time.monotonic()
    try:
        if use_cache:
            findings, _index, cache_stats = analyze_project_cached(
                paths,
                root=args.root,
                rules=rules,
                report_paths=report_paths,
                cache_path=args.cache,
            )
        else:
            findings, _index = analyze_project(
                paths,
                root=args.root,
                rules=rules,
                report_paths=report_paths,
            )
    except FileNotFoundError as exc:
        parser.error(str(exc))
    wall_s = time.monotonic() - t0

    if args.write_baseline:
        write_baseline(findings, args.baseline)
        print(
            f"wrote {len(findings)} finding(s) to {args.baseline}",
            file=sys.stderr,
        )
        return 0

    baseline = (
        load_baseline(args.baseline) if not args.no_baseline else None
    )
    if baseline is not None:
        staleness = stale_baseline_meta(args.baseline)
        if staleness is not None:
            # never silently trust a "clean" verdict vetted under a
            # different (older) rule set
            print(f"warning: {staleness}", file=sys.stderr)
    report = (
        findings if baseline is None else new_findings(findings, baseline)
    )

    if args.format == "json":
        print(json.dumps([f.__dict__ for f in report], indent=2))
    elif args.format == "sarif":
        print(json.dumps(sarif_report(report, args.root), indent=2))
    else:
        for f in report:
            print(f.format())
        suppressed = len(findings) - len(report)
        summary = f"{len(report)} new finding(s)"
        if baseline is not None:
            summary += f", {suppressed} baselined"
            # on a rule/path/changed subset most baselined entries
            # didn't get a chance to fire, so "stale" would be
            # meaningless (and the recommended regen destructive)
            stale = (
                stale_baseline_entries(findings, baseline)
                if full_run
                else {}
            )
            if stale:
                summary += (
                    f"; {sum(stale.values())} baseline entr(ies) no "
                    "longer fire — regenerate with --write-baseline"
                )
        if report_paths is not None:
            summary += (
                f" in {len(report_paths)} changed file(s) "
                f"vs {args.changed}"
            )
        summary += f" [{wall_s:.2f}s]"
        if cache_stats is not None:
            summary += (
                f" (cache {cache_stats['mode']}: "
                f"{cache_stats['reused']} reused, "
                f"{cache_stats['analyzed']} analyzed)"
            )
        print(summary, file=sys.stderr)

    return 1 if report else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `znicz-check | head` closing the pipe early is not a failure
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
