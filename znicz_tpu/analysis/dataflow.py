"""Interprocedural value provenance: bounded vs unbounded-dynamic.

The serving stack's most-defended invariant is *zero new compiled
programs*: every value that becomes a compiled-program identity — a
``static_argnums`` argument, a program-cache/ladder key, a host-side
buffer shape — must range over a SMALL FIXED SET, or each new request
shape silently compiles a new executable.  The repo's discipline is to
pass every dynamic quantity through a bucketing boundary
(``bucket_for``, the x2 window-ladder helpers) before it can reach one
of those sites.  This module is the static model of that discipline: a
three-point provenance lattice

    BOUNDED  <  UNKNOWN  <  UNBOUNDED

where **bounded** covers literals, module-level constants and the
results of recognized bucketing/clamping calls; **unbounded-dynamic**
covers the things that provably range with the request stream —
``len(...)`` of anything, wall-clock reads, loop counters, array
``.size`` reads — and **unknown** is everything the analysis cannot
place (attribute state, unresolvable calls, parameters with no
resolvable call sites).  Rules fire on UNBOUNDED only: unknown values
stay quiet, so the layer errs toward false negatives, never noise.

Propagation is demand-driven and interprocedural over the PR 9 project
index: the provenance of an expression is computed only when a rule
asks (sink sites are rare), pulling

* local bindings (the last textual assignment before the use, so
  ``n = len(p); n = bucket_for(n, L)`` is bounded at later uses),
* function return summaries through the symbol table (a helper that
  returns ``bucket_for(...)`` is itself a boundary; one that returns
  ``len(x)`` taints its callers),
* parameter provenance from the call graph (a parameter is unbounded
  when any resolvable project-internal call site passes an unbounded
  value — the origin string carries the call site),
* attribute-field summaries by field NAME project-wide (``x.bucket``
  is bounded iff every ``<expr>.bucket = ...`` store in the project
  assigns a bounded value).

Known false-negative shapes (documented in docs/STATIC_ANALYSIS.md):
values smuggled through containers (``cfg["n"]``), dataclass/
constructor-kwarg fields (no attribute STORE exists to summarize),
``self.m()`` dispatch across modules, and any binding the one-pass
textual-order approximation misreads inside a loop.  All of these
degrade to UNKNOWN — quiet, never wrong-positive.

Pure stdlib ``ast``; importing this module must never pull in jax.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, NamedTuple, Optional, Tuple

from znicz_tpu.analysis.context import (
    _param_names,
    _positional_names,
    name_is_shadowed,
)

BOUNDED, UNKNOWN, UNBOUNDED = 0, 1, 2

_LEVEL_NAME = {BOUNDED: "bounded", UNKNOWN: "unknown", UNBOUNDED: "unbounded"}


class Prov(NamedTuple):
    """A lattice point plus (for UNBOUNDED) the human-readable origin
    of the dynamic value — carried through joins so the eventual
    finding can say *which* request-varying quantity leaked."""

    level: int
    origin: str = ""


P_BOUNDED = Prov(BOUNDED)
P_UNKNOWN = Prov(UNKNOWN)


def join(a: Prov, b: Prov) -> Prov:
    return a if a.level >= b.level else b


# a call whose terminal name matches is a BUCKETING/CLAMPING BOUNDARY:
# its result ranges over the ladder, not the input.  Over-matching here
# costs a false negative (quiet), never a false positive.
_BUCKET_NAME_RE = re.compile(
    r"(^|_)(bucket|bucketed|rung|window|clamp|snap|quantiz)", re.I
)

_WALL_CLOCK = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.process_time",
}
# builtins that pass their argument's provenance straight through
_PASS_THROUGH = {"int", "float", "abs", "round"}

_MAX_DEPTH = 16  # recursion bound across summaries/params/fields
_MAX_FIELD_SITES = 32  # give up (UNKNOWN) on very hot field names


def is_bucketing_name(dotted: Optional[str]) -> bool:
    if not dotted:
        return False
    return bool(_BUCKET_NAME_RE.search(dotted.rpartition(".")[2]))


class _FnBindings:
    """One function's name bindings in textual order (the flow
    approximation: the last assignment BEFORE the use wins)."""

    __slots__ = ("entries",)

    def __init__(self, fn: ast.AST):
        # name -> [(lineno, kind, payload)] sorted by lineno
        self.entries: Dict[str, List[Tuple[int, str, object]]] = {}
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue  # nested scopes bind their own names
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    self._bind_target(t, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._bind_target(node.target, node.value)
            elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name
            ):
                self._add(node.target.id, node.lineno, "aug", node)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._bind_loop_target(node.target, node.iter)
            elif isinstance(node, ast.withitem) and isinstance(
                node.optional_vars, ast.Name
            ):
                self._add(
                    node.optional_vars.id,
                    node.context_expr.lineno,
                    "expr",
                    node.context_expr,
                )
            stack.extend(ast.iter_child_nodes(node))
        for lst in self.entries.values():
            lst.sort(key=lambda e: e[0])

    def _add(self, name: str, lineno: int, kind: str, payload) -> None:
        self.entries.setdefault(name, []).append((lineno, kind, payload))

    def _bind_target(self, target: ast.AST, value: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self._add(target.id, target.lineno, "expr", value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                # unpacking: each name gets the whole RHS's provenance
                # (an element of an unbounded thing is unbounded-ish;
                # of a bounded tuple, bounded) — conservative join
                self._bind_target(elt, value)

    def _bind_loop_target(self, target: ast.AST, it: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self._add(target.id, target.lineno, "for", (it, 0))
        elif isinstance(target, (ast.Tuple, ast.List)):
            for i, elt in enumerate(target.elts):
                if isinstance(elt, ast.Name):
                    self._add(elt.id, elt.lineno, "for", (it, i))


class DataflowIndex:
    """Demand-driven provenance over a built
    :class:`~znicz_tpu.analysis.project.ProjectIndex`."""

    def __init__(self, index):
        self.index = index
        self._bindings: Dict[int, _FnBindings] = {}
        self._module_consts: Dict[int, Dict[str, ast.AST]] = {}
        self._summary_memo: Dict[int, Prov] = {}
        self._param_memo: Dict[Tuple[int, str], Prov] = {}
        self._field_memo: Dict[str, Prov] = {}
        self._in_progress: set = set()
        self._field_sites: Optional[Dict[str, List]] = None
        self._callers: Optional[Dict[int, List]] = None

    # -- lazy project-wide tables -----------------------------------------

    def _field_assignments(self) -> Dict[str, List]:
        """attr name -> [(info, fn, value expr)] over every
        ``<expr>.attr = value`` store in the project (field-sensitive
        by NAME, object-insensitive — the repo's attribute names are
        distinctive enough that this is the right cost point)."""
        if self._field_sites is None:
            sites: Dict[str, List] = {}
            for info in self.index.modules.values():
                for node in ast.walk(info.tree):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Attribute):
                            sites.setdefault(t.attr, []).append(
                                (info, info.enclosing_function(t), node.value)
                            )
            self._field_sites = sites
        return self._field_sites

    def _call_sites(self) -> Dict[int, List]:
        if self._callers is None:
            self._callers = self.index._call_sites()
        return self._callers

    def _consts(self, info) -> Dict[str, ast.AST]:
        """Module-level simple assignments (last one wins)."""
        key = id(info)
        if key not in self._module_consts:
            out: Dict[str, ast.AST] = {}
            for node in info.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out[t.id] = node.value
                elif (
                    isinstance(node, ast.AnnAssign)
                    and isinstance(node.target, ast.Name)
                    and node.value is not None
                ):
                    out[node.target.id] = node.value
            self._module_consts[key] = out
        return self._module_consts[key]

    def _fn_bindings(self, fn) -> _FnBindings:
        key = id(fn)
        if key not in self._bindings:
            self._bindings[key] = _FnBindings(fn)
        return self._bindings[key]

    # -- provenance of one expression --------------------------------------

    def prov(self, expr: ast.AST, info, depth: int = 0) -> Prov:
        """Provenance of ``expr`` read in ``info``'s module."""
        if depth > _MAX_DEPTH:
            return P_UNKNOWN
        if expr is None:
            return P_BOUNDED
        if isinstance(expr, ast.Constant):
            return P_BOUNDED
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out = P_BOUNDED
            for elt in expr.elts:
                out = join(out, self.prov(elt, info, depth + 1))
            return out
        if isinstance(expr, ast.Dict):
            out = P_BOUNDED
            for v in expr.values:
                out = join(out, self.prov(v, info, depth + 1))
            return out
        if isinstance(expr, ast.Starred):
            return self.prov(expr.value, info, depth + 1)
        if isinstance(expr, ast.Name):
            return self._name_prov(expr, info, depth)
        if isinstance(expr, ast.Attribute):
            return self._attr_prov(expr, info, depth)
        if isinstance(expr, ast.Call):
            return self._call_prov(expr, info, depth)
        if isinstance(expr, ast.BinOp):
            return join(
                self.prov(expr.left, info, depth + 1),
                self.prov(expr.right, info, depth + 1),
            )
        if isinstance(expr, ast.UnaryOp):
            return self.prov(expr.operand, info, depth + 1)
        if isinstance(expr, ast.BoolOp):
            out = P_BOUNDED
            for v in expr.values:
                out = join(out, self.prov(v, info, depth + 1))
            return out
        if isinstance(expr, ast.Compare):
            return P_BOUNDED  # a bool: two-valued by construction
        if isinstance(expr, ast.IfExp):
            return join(
                self.prov(expr.body, info, depth + 1),
                self.prov(expr.orelse, info, depth + 1),
            )
        if isinstance(expr, ast.Subscript):
            # an ELEMENT of a container ranges over the container's
            # contents: ladder[-1] is bounded whatever the index is
            return self.prov(expr.value, info, depth + 1)
        return P_UNKNOWN

    # -- name / attribute / call resolution ---------------------------------

    def _name_prov(self, expr: ast.Name, info, depth: int) -> Prov:
        fn = info.enclosing_function(expr)
        name = expr.id
        cur = fn
        while cur is not None:
            entries = self._fn_bindings(cur).entries.get(name)
            if entries:
                before = [e for e in entries if e[0] < expr.lineno]
                if before:
                    return self._binding_prov(before[-1], info, cur, depth)
                # textual use-before-binding (loop back-edge): join all
                out = P_BOUNDED
                for e in entries:
                    out = join(out, self._binding_prov(e, info, cur, depth))
                return out
            if name in _param_names(cur):
                return self._param_prov(cur, name, info, depth)
            cur = info.enclosing_function(cur)
        const = self._consts(info).get(name)
        if const is not None:
            return self.prov(const, info, depth + 1)
        return P_UNKNOWN

    def _binding_prov(self, entry, info, fn, depth: int) -> Prov:
        lineno, kind, payload = entry
        if kind == "expr":
            return self.prov(payload, info, depth + 1)
        if kind == "aug":
            # n += ... inside a loop is a loop-accumulated counter:
            # it ranges with the iteration count
            node = payload
            cur = info.parents.get(node)
            while cur is not None and cur is not fn:
                if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                    return Prov(
                        UNBOUNDED,
                        f"loop-accumulated counter at {info.path}:{lineno}",
                    )
                cur = info.parents.get(cur)
            return self.prov(node.value, info, depth + 1)
        if kind == "for":
            it, pos = payload
            if isinstance(it, ast.Call):
                target = info.resolved(it.func)
                if target == "range":
                    out = P_BOUNDED
                    for a in it.args:
                        out = join(out, self.prov(a, info, depth + 1))
                    if out.level == UNBOUNDED:
                        return Prov(
                            UNBOUNDED,
                            f"loop counter over a dynamic range at "
                            f"{info.path}:{lineno}",
                        )
                    # range over a config bound stays at the bound's
                    # own provenance (UNKNOWN config never fires)
                    return out
                if target == "enumerate" and pos == 0:
                    return Prov(
                        UNBOUNDED,
                        f"enumerate() loop counter at {info.path}:{lineno}",
                    )
            itp = self.prov(it, info, depth + 1)
            if itp.level == UNBOUNDED:
                return itp
            return P_UNKNOWN
        return P_UNKNOWN

    def _attr_prov(self, expr: ast.Attribute, info, depth: int) -> Prov:
        if expr.attr in ("size", "nbytes"):
            return Prov(
                UNBOUNDED,
                f"array .{expr.attr} read at {info.path}:{expr.lineno}",
            )
        key = expr.attr
        if key in self._field_memo:
            return self._field_memo[key]
        token = ("field", key)
        if token in self._in_progress:
            return P_UNKNOWN
        sites = self._field_assignments().get(key)
        if not sites:
            return P_UNKNOWN  # constructor-kwarg field etc.: no stores
        if len(sites) > _MAX_FIELD_SITES:
            self._field_memo[key] = P_UNKNOWN
            return P_UNKNOWN
        self._in_progress.add(token)
        try:
            out = P_BOUNDED
            for sinfo, _sfn, value in sites:
                p = self.prov(value, sinfo, depth + 1)
                if p.level == UNBOUNDED:
                    p = Prov(
                        UNBOUNDED,
                        f"field '.{key}' assigned unbounded "
                        f"({p.origin})",
                    )
                out = join(out, p)
        finally:
            self._in_progress.discard(token)
        self._field_memo[key] = out
        return out

    def _call_prov(self, expr: ast.Call, info, depth: int) -> Prov:
        resolved = info.resolved(expr.func)
        if is_bucketing_name(resolved or self._attr_name(expr.func)):
            return P_BOUNDED
        if resolved in _WALL_CLOCK:
            return Prov(
                UNBOUNDED,
                f"wall-clock read at {info.path}:{expr.lineno}",
            )
        if resolved == "len":
            return Prov(
                UNBOUNDED, f"len(...) at {info.path}:{expr.lineno}"
            )
        if resolved == "min":
            # clamping against any bounded bound caps the range
            provs = [self.prov(a, info, depth + 1) for a in expr.args]
            if any(p.level == BOUNDED for p in provs):
                return P_BOUNDED
            out = P_BOUNDED
            for p in provs:
                out = join(out, p)
            return out
        if resolved == "max":
            out = P_BOUNDED
            for a in expr.args:
                out = join(out, self.prov(a, info, depth + 1))
            return out
        if resolved in _PASS_THROUGH and expr.args:
            return self.prov(expr.args[0], info, depth + 1)
        target = self._resolve_callee(expr, info)
        if target is not None:
            tinfo, fn = target
            return self._summary(fn, tinfo, depth)
        return P_UNKNOWN

    @staticmethod
    def _attr_name(func: ast.AST) -> Optional[str]:
        return func.attr if isinstance(func, ast.Attribute) else None

    def _resolve_callee(self, call: ast.Call, info):
        """The called FunctionDef, when statically resolvable: a plain
        project function through the symbol table, or ``self.m()``
        within the enclosing class."""
        func = call.func
        if isinstance(func, ast.Name):
            if name_is_shadowed(info, func, func.id):
                return None
            hit = self.index.resolve_symbol(info.resolved(func), home=info)
            if hit is not None and hit[1] is not None:
                return hit
            return None
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            cls = self._enclosing_class(call, info)
            if cls is not None:
                for sub in cls.body:
                    if (
                        isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                        )
                        and sub.name == func.attr
                    ):
                        return (info, sub)
            return None
        if isinstance(func, ast.Attribute):
            hit = self.index.resolve_symbol(info.resolved(func), home=info)
            if hit is not None and hit[1] is not None:
                return hit
        return None

    @staticmethod
    def _enclosing_class(node, info) -> Optional[ast.ClassDef]:
        cur = info.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = info.parents.get(cur)
        return None

    # -- interprocedural summaries ------------------------------------------

    def _summary(self, fn, info, depth: int) -> Prov:
        """Join of a function's return-expression provenances: the
        callee-side half of interprocedural propagation."""
        key = id(fn)
        if key in self._summary_memo:
            return self._summary_memo[key]
        token = ("summary", key)
        if token in self._in_progress:
            return P_UNKNOWN
        if isinstance(fn, ast.Lambda):
            return P_UNKNOWN
        self._in_progress.add(token)
        try:
            out = P_BOUNDED
            saw_return = False
            stack = list(ast.iter_child_nodes(fn))
            while stack:
                node = stack.pop()
                if isinstance(
                    node,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                if isinstance(node, (ast.Yield, ast.YieldFrom)):
                    out = P_UNKNOWN
                    saw_return = True
                    continue
                if isinstance(node, ast.Return) and node.value is not None:
                    saw_return = True
                    out = join(out, self.prov(node.value, info, depth + 1))
                stack.extend(ast.iter_child_nodes(node))
            if not saw_return:
                out = P_BOUNDED  # returns None
        finally:
            self._in_progress.discard(token)
        self._summary_memo[key] = out
        return out

    def _param_prov(self, fn, name: str, info, depth: int) -> Prov:
        """Join over what resolvable project-internal call sites pass
        for ``fn``'s parameter ``name`` — the caller-side half."""
        key = (id(fn), name)
        if key in self._param_memo:
            return self._param_memo[key]
        token = ("param", key)
        if token in self._in_progress:
            return P_UNKNOWN
        callers = self._call_sites().get(id(fn), [])
        if not callers:
            return P_UNKNOWN
        pos = _positional_names(fn)
        self._in_progress.add(token)
        try:
            out = P_BOUNDED
            for cinfo, call in callers:
                matched = None
                for i, arg in enumerate(call.args):
                    if isinstance(arg, ast.Starred):
                        matched = None
                        out = P_UNKNOWN
                        break
                    if i < len(pos) and pos[i] == name:
                        matched = arg
                        break
                for kw in call.keywords:
                    if kw.arg == name:
                        matched = kw.value
                if matched is None:
                    continue
                p = self.prov(matched, cinfo, depth + 1)
                if p.level == UNBOUNDED:
                    p = Prov(
                        UNBOUNDED,
                        f"{p.origin}, via call at "
                        f"{cinfo.path}:{call.lineno}",
                    )
                out = join(out, p)
        finally:
            self._in_progress.discard(token)
        self._param_memo[key] = out
        return out


def get_dataflow(index) -> DataflowIndex:
    """Memoized per-:class:`ProjectIndex` dataflow layer (several
    rules share one index; the field/caller tables are built once)."""
    df = getattr(index, "_dataflow", None)
    if df is None:
        df = DataflowIndex(index)
        index._dataflow = df
    return df
