"""Project-wide analysis: cross-module transforms and the call graph.

The per-module :class:`~znicz_tpu.analysis.context.TracedIndex` only
sees transform applications spelled in the SAME module as the function
definition — ``jax.jit(step)`` in ``bench.py`` where ``step`` lives in
``workflow/standard.py`` used to mark nothing (the ROADMAP's carried
"same-module caveat").  This module is the whole-project upgrade:

* **Symbol table** — every ``.py`` under the analyzed tree is parsed
  once; a dotted-name index maps ``znicz_tpu.workflow.standard.step``
  to the ``FunctionDef`` that owns it (module-level functions and
  one-level class methods), resolving each module's own import aliases.
* **Cross-module transform propagation** — every ``jax.jit(f)`` /
  ``grad(f)`` / ``lax.scan(body, ...)`` call-form application is
  resolved against the symbol table; when the target lives in a
  DIFFERENT module, the target's own :class:`TracedIndex` is marked, so
  ZNC001/ZNC002/ZNC006 fire inside the definition no matter where the
  transform was applied.  ``static_argnums``/``static_argnames`` and
  ``partial``-bound names are honored exactly like the local pass.
* **Call graph + chain marking** — a module-level helper reachable
  ONLY from traced callers (every project-internal call site sits in
  traced code) is itself analyzed as traced: its parameters are
  classified traced/static from what the call sites actually pass
  (a literal stays static; a traced name makes the parameter traced),
  and any finding inside it is RE-ANCHORED to the traced entry point
  with the call chain in the message — the hazard is reported where
  the tracer enters, which is where the fix (a static arg, a
  ``lax.cond``) must be applied.

The pass is still a static approximation: helpers also called from
host code stay unmarked (the host call sites prove a concrete-Python
contract exists), methods reached through ``self`` are out of scope,
and dynamic dispatch is invisible.  Everything here is pure stdlib
``ast`` — importing this module must never pull in jax.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from znicz_tpu.analysis.context import (
    _param_names,
    _positional_names,
    _static_names_from_kwargs,
    name_is_shadowed,
    unwrap_partial,
)
from znicz_tpu.analysis.engine import (
    Finding,
    ModuleInfo,
    iter_py_files,
)

# rules whose findings inside a chain-marked helper are re-anchored to
# the traced entry point (the rules that key on traced context)
CHAIN_RULES = ("ZNC001", "ZNC002", "ZNC006")


def module_name(rel_path: str) -> str:
    """``znicz_tpu/services/engine.py`` -> ``znicz_tpu.services.engine``
    (posix separators; ``__init__.py`` names the package itself)."""
    name = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    elif name == "__init__":
        name = ""
    return name


def _expr_uses(node: ast.AST, names: Set[str]) -> bool:
    """Does the expression read any of ``names``?"""
    return any(
        isinstance(n, ast.Name) and n.id in names
        for n in ast.walk(node)
    )


class _Chain:
    """One helper marked traced through the call graph."""

    __slots__ = ("info", "fn", "qual", "chain", "entry_info", "entry_fn")

    def __init__(self, info, fn, qual):
        self.info = info  # ModuleInfo owning the helper
        self.fn = fn  # the helper's FunctionDef
        self.qual = qual  # "module.helper"
        self.chain: List[str] = []  # entry ... helper qualnames
        self.entry_info: Optional[ModuleInfo] = None
        self.entry_fn = None  # the traced entry FunctionDef

    def contains(self, line: int) -> bool:
        end = getattr(self.fn, "end_lineno", self.fn.lineno)
        return self.fn.lineno <= line <= end


class ProjectIndex:
    """Parsed project + cross-module traced-context propagation.

    Build with :meth:`build`; the per-module :class:`ModuleInfo`
    objects (``.modules``, keyed by repo-relative path) already carry
    the cross-module marks when construction returns, so running the
    ordinary rules over them IS the project-wide analysis.
    """

    def __init__(self, root: str):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}  # rel path -> info
        self.by_name: Dict[str, ModuleInfo] = {}  # dotted name -> info
        # dotted module name -> {qualname -> FunctionDef}: module-level
        # functions plus one-level class methods
        self.defs: Dict[str, Dict[str, ast.AST]] = {}
        self.syntax_findings: List[Finding] = []
        # cross-module transform applications, for introspection/tests:
        # {"transform", "site", "site_line", "target"}
        self.applications: List[Dict] = []
        self._chains: List[_Chain] = []
        # (id(fn)) -> _Chain for entry resolution through nested chains
        self._chain_by_fn: Dict[int, _Chain] = {}
        self._sites: Optional[
            Dict[int, List[Tuple[ModuleInfo, ast.Call]]]
        ] = None

    # -- construction ------------------------------------------------------

    @classmethod
    def build(
        cls, paths: Sequence[str], root: Optional[str] = None
    ) -> "ProjectIndex":
        root = os.path.abspath(root or os.getcwd())
        index = cls(root)
        for file in iter_py_files(paths):
            rel = os.path.relpath(os.path.abspath(file), root).replace(
                os.sep, "/"
            )
            with open(file, encoding="utf-8") as f:
                source = f.read()
            index.add_module(source, rel)
        index.link()
        return index

    def add_module(self, source: str, rel_path: str) -> None:
        """Parse one module into the index (syntax errors become
        ZNC000 findings, exactly like the per-file engine)."""
        try:
            info = ModuleInfo(source, rel_path, self.root)
        except SyntaxError as exc:
            self.syntax_findings.append(
                Finding(
                    rule="ZNC000",
                    severity="error",
                    path=rel_path,
                    line=exc.lineno or 0,
                    col=(exc.offset or 0),
                    message=f"syntax error: {exc.msg}",
                    symbol="<module>",
                    snippet="",
                )
            )
            return
        self.modules[rel_path] = info
        name = module_name(rel_path)
        self.by_name[name] = info
        defs: Dict[str, ast.AST] = {}
        for node in info.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        defs[f"{node.name}.{sub.name}"] = sub
        self.defs[name] = defs

    def link(self) -> None:
        """Resolve cross-module transform applications, then chain-mark
        traced-only helpers.  Idempotent per build."""
        self._link_transforms()
        self._chain_mark()

    # -- symbol resolution -------------------------------------------------

    def resolve_symbol(
        self, dotted: Optional[str], home: Optional[ModuleInfo] = None
    ) -> Optional[Tuple[ModuleInfo, ast.AST]]:
        """``pkg.mod.fn`` (alias-resolved) -> (owning ModuleInfo,
        FunctionDef), via the longest known module-name prefix.  A bare
        name resolves against ``home``'s own module-level defs."""
        if not dotted:
            return None
        parts = dotted.split(".")
        if len(parts) == 1:
            if home is None:
                return None
            name = module_name(home.path)
            fn = self.defs.get(name, {}).get(dotted)
            return (home, fn) if fn is not None else None
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            info = self.by_name.get(mod)
            if info is None:
                continue
            fn = self.defs[mod].get(".".join(parts[i:]))
            return (info, fn) if fn is not None else None
        return None

    def _resolve_callable(
        self, info: ModuleInfo, node: ast.AST
    ) -> List[Tuple[ModuleInfo, ast.AST, Set[str]]]:
        """A transform's callable argument -> [(owning module, def,
        partial-bound names)], cross-module.  Shares the local pass's
        ``partial(body, ...)`` unwrapping (names the partial binds are
        trace-time constants)."""
        node, n_pos, kwnames = unwrap_partial(info, node)
        if not isinstance(node, (ast.Name, ast.Attribute)):
            return []
        if isinstance(node, ast.Name) and name_is_shadowed(
            info, node, node.id
        ):
            return []  # a parameter/local, never the module-level def
        hit = self.resolve_symbol(info.resolved(node), home=info)
        if hit is None:
            return []
        tinfo, fn = hit
        bound = set(kwnames)
        bound.update(_positional_names(fn)[:n_pos])
        return [(tinfo, fn, bound)]

    # -- cross-module transforms -------------------------------------------

    def _link_transforms(self) -> None:
        from znicz_tpu.analysis.context import LAX_BODIES

        for info in self.modules.values():
            ti = info.traced
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Call):
                    continue
                base, kws = ti._wrapper_call(node)
                if base is not None and node.args:
                    for tinfo, fn, bound in self._resolve_callable(
                        info, node.args[0]
                    ):
                        if tinfo is info:
                            continue  # the local pass already saw it
                        static = set(bound)
                        static |= _static_names_from_kwargs(fn, kws)
                        tinfo.traced.mark_traced(fn, static)
                        self._record(base, info, node, tinfo, fn)
                    continue
                lax_name = (info.resolved(node.func) or "").rpartition(
                    "."
                )[2]
                head = (info.resolved(node.func) or "").rpartition(".")[0]
                body_slots = (
                    LAX_BODIES.get(lax_name)
                    if head
                    in (
                        "jax",
                        "lax",
                        "jax.lax",
                    )
                    else None
                )
                if body_slots:
                    for i in body_slots:
                        if i < len(node.args):
                            for tinfo, fn, bound in self._resolve_callable(
                                info, node.args[i]
                            ):
                                if tinfo is info:
                                    continue
                                tinfo.traced.mark_traced(fn, set(bound))
                                self._record(
                                    lax_name, info, node, tinfo, fn
                                )

    def _record(self, transform, info, node, tinfo, fn) -> None:
        self.applications.append(
            {
                "transform": transform,
                "site": info.path,
                "site_line": getattr(node, "lineno", 0),
                "target": f"{module_name(tinfo.path)}."
                f"{tinfo.qualname(fn)}",
            }
        )

    # -- call graph + chain marking ----------------------------------------

    def _call_sites(self) -> Dict[int, List[Tuple[ModuleInfo, ast.Call]]]:
        """Project-internal call sites per callee: id(def) ->
        [(caller module, call node)].  Only plain-function calls that
        resolve through the symbol table; ``self.m()`` dispatch and
        anything dynamic stays invisible (conservative).  Memoized —
        the chain-marking pass and the dataflow layer share one walk."""
        if self._sites is not None:
            return self._sites
        sites: Dict[int, List[Tuple[ModuleInfo, ast.Call]]] = {}
        self._def_meta: Dict[int, Tuple[ModuleInfo, ast.AST]] = {}
        for info in self.modules.values():
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not isinstance(
                    node.func, (ast.Name, ast.Attribute)
                ):
                    continue
                if isinstance(node.func, ast.Attribute):
                    # self.m() / obj.m(): method dispatch, out of scope
                    base = node.func.value
                    while isinstance(base, ast.Attribute):
                        base = base.value
                    if not isinstance(base, ast.Name):
                        continue
                    if (
                        base.id not in info.import_aliases
                        and base.id not in info.from_imports
                    ):
                        continue
                elif name_is_shadowed(info, node.func, node.func.id):
                    # `outer(x, helper)` calling its PARAMETER must not
                    # be attributed to an unrelated module-level def of
                    # the same name (and then chain-marked off it)
                    continue
                hit = self.resolve_symbol(
                    info.resolved(node.func), home=info
                )
                if hit is None or hit[1] is None:
                    continue
                tinfo, fn = hit
                if isinstance(fn, ast.AsyncFunctionDef):
                    continue  # awaited elsewhere; not a sync chain
                sites.setdefault(id(fn), []).append((info, node))
                self._def_meta[id(fn)] = (tinfo, fn)
        self._sites = sites
        return sites

    def _site_traced_params(
        self, caller_info: ModuleInfo, call: ast.Call, fn
    ) -> Set[str]:
        """Which of ``fn``'s parameters receive traced values at this
        call site.  Literals and names outside the caller's traced set
        stay static — so ``helper(x, training=False)`` from a jitted
        caller marks only ``x`` traced."""
        traced = caller_info.traced.traced_param_names(call)
        pos = _positional_names(fn)
        vararg = fn.args.vararg.arg if fn.args.vararg else None
        out: Set[str] = set()
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                out.update(pos[i:])  # unknown spread: conservative
                break
            name = pos[i] if i < len(pos) else vararg
            if name and _expr_uses(arg, traced):
                out.add(name)
        for kw in call.keywords:
            if kw.arg and _expr_uses(kw.value, traced):
                out.add(kw.arg)
        return out

    def _chain_mark(self) -> None:
        sites = self._call_sites()
        changed = True
        while changed:
            changed = False
            for fid, callers in sites.items():
                tinfo, fn = self._def_meta[fid]
                if tinfo.traced.is_traced(fn):
                    continue
                if not all(
                    cinfo.traced.in_traced_code(call)
                    for cinfo, call in callers
                ):
                    continue
                traced_params: Set[str] = set()
                for cinfo, call in callers:
                    traced_params |= self._site_traced_params(
                        cinfo, call, fn
                    )
                static = set(_param_names(fn)) - traced_params
                tinfo.traced.mark_traced(fn, static)
                qual = f"{module_name(tinfo.path)}.{tinfo.qualname(fn)}"
                chain = _Chain(tinfo, fn, qual)
                # entry: the first call site's own chain, extended
                cinfo, call = callers[0]
                caller_fn = cinfo.enclosing_function(call)
                prior = self._chain_by_fn.get(id(caller_fn))
                if prior is not None and prior.entry_fn is not None:
                    chain.entry_info = prior.entry_info
                    chain.entry_fn = prior.entry_fn
                    chain.chain = prior.chain + [qual]
                else:
                    chain.entry_info = cinfo
                    chain.entry_fn = caller_fn
                    caller_qual = (
                        f"{module_name(cinfo.path)}."
                        f"{cinfo.qualname(call)}"
                    )
                    chain.chain = [caller_qual, qual]
                self._chains.append(chain)
                self._chain_by_fn[id(fn)] = chain
                changed = True

    # -- finding post-processing -------------------------------------------

    def chains(self) -> List[Dict]:
        """Chain-marked helpers, for tests/introspection."""
        return [
            {"helper": c.qual, "chain": list(c.chain), "path": c.info.path}
            for c in self._chains
        ]

    def relocate(self, findings: Iterable[Finding]) -> List[Finding]:
        """Re-anchor traced-context findings that sit inside a
        chain-marked helper to the traced ENTRY point, carrying the
        call chain (and the helper's real location) in the message —
        the entry is where the fix applies."""
        out: List[Finding] = []
        for f in findings:
            chain = None
            if f.rule in CHAIN_RULES:
                for c in self._chains:
                    if c.info.path == f.path and c.contains(f.line):
                        chain = c
                        break
            if chain is None or chain.entry_fn is None:
                out.append(f)
                continue
            einfo, efn = chain.entry_info, chain.entry_fn
            out.append(
                Finding(
                    rule=f.rule,
                    severity=f.severity,
                    path=einfo.path,
                    line=efn.lineno,
                    col=efn.col_offset + 1,
                    message=(
                        f"{f.message} [in helper '{chain.qual}' at "
                        f"{f.path}:{f.line}, reachable only from traced "
                        f"code via {' -> '.join(chain.chain)}]"
                    ),
                    symbol=einfo.qualname(efn),
                    snippet=einfo.snippet(efn.lineno),
                )
            )
        out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return out


def project_rule_findings(index: ProjectIndex, rules) -> List[Finding]:
    """Run the PROJECT rules (``rule.project = True`` — dataflow,
    lock-order, blocking-under-lock) over a built index.  Suppression
    applies via the OWNING module's pragmas, exactly like per-module
    findings; findings anchored in files outside the index (never the
    case today) pass through unsuppressed."""
    out: List[Finding] = []
    for rule in rules:
        if not getattr(rule, "project", False):
            continue
        for finding in rule.project_check(index):
            info = index.modules.get(finding.path)
            if info is None or not info.suppressed(finding):
                out.append(finding)
    return out


def analyze_project(
    paths: Sequence[str],
    *,
    root: Optional[str] = None,
    rules: Optional[Sequence] = None,
    report_paths: Optional[Set[str]] = None,
) -> Tuple[List[Finding], ProjectIndex]:
    """Whole-project analysis: one :class:`ProjectIndex` over every
    ``.py`` under ``paths``, the ordinary rules run per module against
    the cross-module-marked trees, chain findings re-anchored, then
    the project rules (dataflow/lock-order/blocking-under-lock) run
    once over the whole index.

    ``report_paths`` (repo-relative, posix) restricts which files'
    findings are RETURNED — the index is still built over everything,
    so cross-module results stay correct (the ``--changed`` contract).
    Returns ``(findings, index)``.
    """
    if rules is None:
        from znicz_tpu.analysis.rules import get_rules

        rules = get_rules()
    root = os.path.abspath(root or os.getcwd())
    index = ProjectIndex.build(paths, root)
    findings: List[Finding] = list(index.syntax_findings)
    for info in index.modules.values():
        for rule in rules:
            if getattr(rule, "project", False):
                continue
            for finding in rule.check(info):
                if not info.suppressed(finding):
                    findings.append(finding)
    findings = index.relocate(findings)
    findings.extend(project_rule_findings(index, rules))
    if report_paths is not None:
        findings = [f for f in findings if f.path in report_paths]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, index
