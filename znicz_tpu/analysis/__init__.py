"""znicz-check: AST-based JAX-hygiene & sharding-consistency analyzer.

The reference stack had no machine-checkable correctness tooling — unit
wiring and device plumbing were validated only at runtime (PAPER.md flags
this as the reconstruction risk).  This subsystem closes the gap for the
rebuild's dominant *silent* failure modes: tracer leaks, retrace storms,
``PartitionSpec`` axes that don't exist on the mesh, PRNG key reuse,
and serving-tier thread-safety drift (lock-discipline races, silently
dying background threads) — none of which any test tier catches before
an expensive TPU run (or a paging incident).  Analysis is
PROJECT-WIDE (:mod:`znicz_tpu.analysis.project`): transforms applied
in one module mark functions defined in another, and helpers reachable
only from traced callers are reported at the traced entry point with
the call chain.

Usage::

    python -m znicz_tpu.analysis znicz_tpu/            # report findings
    python -m znicz_tpu.analysis --list-rules          # rule catalog
    python -m znicz_tpu.analysis --write-baseline      # grandfather

Findings are identified by stable rule IDs (``ZNC001``..).  Pre-existing
findings live in ``tools/znicz_check_baseline.json``; the tier-1 gate
(``tests/test_static_analysis.py``) fails only on *new* findings.
Intentional violations are exempted inline::

    t = time.time()  # znicz-check: disable=ZNC007 -- once per epoch

See docs/STATIC_ANALYSIS.md for the rule catalog and baseline workflow.
"""

from znicz_tpu.analysis.cache import (  # noqa: F401
    analyze_project_cached,
)
from znicz_tpu.analysis.engine import (  # noqa: F401
    ANALYZER_VERSION,
    Finding,
    analyze_paths,
    analyze_source,
    baseline_meta,
    load_baseline,
    new_findings,
    stale_baseline_meta,
    write_baseline,
)
from znicz_tpu.analysis.project import (  # noqa: F401
    ProjectIndex,
    analyze_project,
)
from znicz_tpu.analysis.rules import RULES, get_rules  # noqa: F401
