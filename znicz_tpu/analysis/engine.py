"""Analyzer engine: module parsing, pragmas, baselines, reporting.

Pure stdlib ``ast`` — importing this module must never pull in jax (the
CLI has to run on a build host with no accelerator stack warmed up).

Suppression model (mirrors pylint's, with a stable-fingerprint baseline
like ruff's):

* inline pragma ``# znicz-check: disable=ZNC001[,ZNC002|all]`` on the
  flagged line;
* file-level pragma ``# znicz-check: disable-file=ZNC003`` on any line
  of the file (conventionally the docstring's vicinity);
* baseline file: a fingerprint multiset of grandfathered findings.
  Fingerprints are ``rule::path::symbol::snippet`` — line numbers are
  deliberately absent so unrelated edits above a finding don't churn
  the baseline.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import tokenize
from collections import Counter
from io import StringIO
from typing import Dict, Iterable, List, Optional, Sequence, Set

SEVERITIES = ("error", "warning")

# Bumped whenever finding semantics change (new rules, dataflow layer,
# fingerprint format): the incremental cache and the baseline's
# staleness check both key on it.
ANALYZER_VERSION = "2.0"

_PRAGMA_RE = re.compile(
    r"#\s*znicz-check:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\s]+)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit, addressable by a stable fingerprint."""

    rule: str
    severity: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str
    symbol: str  # enclosing qualname, or "<module>"
    snippet: str  # stripped source of the flagged line

    @property
    def fingerprint(self) -> str:
        # No line number: the baseline must survive edits elsewhere in
        # the file.  Identical lines in one symbol are disambiguated by
        # the baseline's multiset (count) semantics, not the key.
        return f"{self.rule}::{self.path}::{self.symbol}::{self.snippet}"

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} [{self.severity}] {self.message}"
        )


class ModuleInfo:
    """Parsed module + the shared indexes every rule needs.

    ``root`` is the absolute directory the analyzed tree is rooted at
    (when known) — rules that consult sibling files (ZNC003's mesh.py
    axis declarations) resolve them against the TREE UNDER ANALYSIS,
    not the installed analyzer's own checkout.
    """

    def __init__(self, source: str, path: str, root: Optional[str] = None):
        self.path = path
        self.root = root
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # child -> parent (rules walk up to find enclosing funcs/loops)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.line_pragmas, self.file_pragmas = _parse_pragmas(source)
        # alias -> dotted module name ("np" -> "numpy", "jnp" -> "jax.numpy")
        self.import_aliases: Dict[str, str] = {}
        # name -> dotted origin for from-imports ("P" -> "jax.sharding.PartitionSpec")
        self.from_imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )
        from znicz_tpu.analysis.context import TracedIndex

        self.traced = TracedIndex(self)

    # -- node helpers ----------------------------------------------------
    def qualname(self, node: ast.AST) -> str:
        """Dotted name of the enclosing defs, e.g. ``Workflow.run.body``."""
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(
                cur,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return cur
            cur = self.parents.get(cur)
        return None

    def dotted(self, node: ast.AST) -> Optional[str]:
        """``jax.random.split`` for an Attribute/Name chain, else None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    def resolved(self, node: ast.AST) -> Optional[str]:
        """Dotted name with the module's own aliases expanded: with
        ``import numpy as np``, ``np.sum`` resolves to ``numpy.sum``;
        with ``from jax.sharding import PartitionSpec as P``, ``P``
        resolves to ``jax.sharding.PartitionSpec``."""
        name = self.dotted(node)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        if head in self.from_imports:
            head = self.from_imports[head]
        elif head in self.import_aliases:
            head = self.import_aliases[head]
        return f"{head}.{rest}" if rest else head

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(
        self,
        rule: str,
        severity: str,
        node: ast.AST,
        message: str,
    ) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(
            rule=rule,
            severity=severity,
            path=self.path,
            line=line,
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            symbol=self.qualname(node),
            snippet=self.snippet(line),
        )

    def suppressed(self, finding: Finding) -> bool:
        for scope in (
            self.file_pragmas,
            self.line_pragmas.get(finding.line, set()),
        ):
            if "all" in scope or finding.rule in scope:
                return True
        return False


def _parse_pragmas(source: str):
    """Tokenize for comments (robust against ``#`` inside strings)."""
    line_pragmas: Dict[int, Set[str]] = {}
    file_pragmas: Set[str] = set()
    try:
        tokens = tokenize.generate_tokens(StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            kind = m.group(1)
            rules = {
                r.strip() for r in m.group(2).split(",") if r.strip()
            }
            if kind == "disable-file":
                file_pragmas |= rules
            else:
                line_pragmas.setdefault(tok.start[0], set()).update(rules)
    # znicz-check: disable=ZNC008 -- half-written file: pragmas just
    # don't apply; the ast.parse SyntaxError is the real report
    except tokenize.TokenError:  # znicz-check: disable=ZNC008
        pass
    return line_pragmas, file_pragmas


# -- running rules -------------------------------------------------------


def analyze_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Sequence] = None,
    root: Optional[str] = None,
) -> List[Finding]:
    """Analyze one module's source; pragma suppression applied.

    PER-MODULE view only: transform applications in other modules are
    invisible here.  The CLI and the tier-1 gate run
    :func:`znicz_tpu.analysis.project.analyze_project` instead, which
    cross-module-marks every ModuleInfo before the rules see it."""
    from znicz_tpu.analysis.rules import get_rules

    info = ModuleInfo(source, path, root)
    out: List[Finding] = []
    for rule in rules if rules is not None else get_rules():
        for finding in rule.check(info):
            if not info.suppressed(finding):
                out.append(finding)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if not os.path.exists(path):
            # a typo'd target must not report "clean" on zero files
            raise FileNotFoundError(f"no such file or directory: {path}")
        if os.path.isfile(path):
            yield path
        else:
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [
                    d
                    for d in dirnames
                    if d not in ("__pycache__", ".git")
                ]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)


def analyze_paths(
    paths: Sequence[str],
    *,
    root: Optional[str] = None,
    rules: Optional[Sequence] = None,
) -> List[Finding]:
    """Analyze every ``.py`` under ``paths``, each module in
    isolation (see :func:`analyze_source` for the project-wide
    alternative).  Finding paths (and thus fingerprints) are relative
    to ``root`` (default: cwd) with posix separators, so baselines are
    machine-independent."""
    if rules is None:
        from znicz_tpu.analysis.rules import get_rules

        rules = get_rules()  # resolve once, not per file
    root = os.path.abspath(root or os.getcwd())
    out: List[Finding] = []
    for file in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(file), root).replace(
            os.sep, "/"
        )
        with open(file, encoding="utf-8") as f:
            source = f.read()
        try:
            out.extend(analyze_source(source, rel, rules, root=root))
        except SyntaxError as exc:
            out.append(
                Finding(
                    rule="ZNC000",
                    severity="error",
                    path=rel,
                    line=exc.lineno or 0,
                    col=(exc.offset or 0),
                    message=f"syntax error: {exc.msg}",
                    symbol="<module>",
                    snippet="",
                )
            )
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


# -- baseline ------------------------------------------------------------


def load_baseline(path: str) -> Counter:
    """Baseline file -> fingerprint multiset (missing file = empty)."""
    if not os.path.exists(path):
        return Counter()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return Counter(data.get("findings", {}))


def baseline_meta(path: str) -> Dict:
    """The ``analyzer`` stamp a baseline was written under (analyzer
    version + the rule-id set active at write time).  Empty for a
    missing file or a pre-versioning baseline — callers treat both as
    "provenance unknown" and warn."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    meta = data.get("analyzer")
    return meta if isinstance(meta, dict) else {}


def stale_baseline_meta(path: str) -> Optional[str]:
    """Human-readable staleness verdict for a baseline's analyzer
    stamp, or None when the stamp matches the active rule set.  A
    baseline regenerated under an OLDER rule set predates the newer
    rules' findings: its "clean" verdict silently says nothing about
    them, so the CLI warns instead of trusting it."""
    from znicz_tpu.analysis.rules import RULES

    if not os.path.exists(path):
        return None  # no baseline at all: nothing to mistrust
    meta = baseline_meta(path)
    if not meta:
        return (
            "baseline has no analyzer stamp (written before rule-set "
            "versioning); regenerate with --write-baseline"
        )
    current = sorted(RULES)
    recorded = meta.get("rules", [])
    missing = sorted(set(current) - set(recorded))
    if missing:
        return (
            "baseline predates rule(s) "
            + ", ".join(missing)
            + " — its entries were vetted without them; regenerate "
            "with --write-baseline"
        )
    if meta.get("version") != ANALYZER_VERSION:
        return (
            f"baseline was written by analyzer "
            f"{meta.get('version')!r} (current {ANALYZER_VERSION!r}); "
            "regenerate with --write-baseline"
        )
    return None


def write_baseline(findings: Sequence[Finding], path: str) -> None:
    from znicz_tpu.analysis.rules import RULES

    counts = Counter(f.fingerprint for f in findings)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "comment": (
                    "znicz-check grandfathered findings; regenerate "
                    "with python -m znicz_tpu.analysis --write-baseline"
                ),
                "version": 1,
                # provenance stamp: which analyzer + rule set vetted
                # these entries — a later run under a NEWER rule set
                # warns instead of silently trusting a stale verdict
                "analyzer": {
                    "version": ANALYZER_VERSION,
                    "rules": sorted(RULES),
                },
                "findings": {k: counts[k] for k in sorted(counts)},
            },
            f,
            indent=2,
        )
        f.write("\n")


def new_findings(
    findings: Sequence[Finding], baseline: Counter
) -> List[Finding]:
    """Findings beyond the baseline's per-fingerprint allowance.  When a
    fingerprint occurs more times than baselined, the LAST occurrences
    (file order) are reported — the earliest are assumed grandfathered."""
    remaining = Counter(baseline)
    out: List[Finding] = []
    for f in findings:
        if remaining[f.fingerprint] > 0:
            remaining[f.fingerprint] -= 1
        else:
            out.append(f)
    return out


def stale_baseline_entries(
    findings: Sequence[Finding], baseline: Counter
) -> Counter:
    """Baselined fingerprints that no longer occur (burned down) — the
    CLI reports these so the baseline can be re-shrunk, keeping the debt
    ledger honest."""
    current = Counter(f.fingerprint for f in findings)
    stale = Counter()
    for fp, n in baseline.items():
        extra = n - current.get(fp, 0)
        if extra > 0:
            stale[fp] = extra
    return stale
