"""Shared lock model for the serving-tier concurrency rules.

ZNC012 (lock-discipline races) established the per-class picture: which
attributes are locks, which methods the threads enter.  ZNC015
(lock-order deadlocks) and ZNC016 (blocking-under-lock) need the step
further: WHAT HAPPENS WHILE A LOCK IS HELD — which other locks get
acquired (directly, or transitively through ``self.m()`` calls,
cross-object ``self.attr.m()`` calls typed from ``__init__``
assignments, and plain project-function calls through the PR 9 call
graph), and which recognized blocking operations run inside the
critical section.  This module computes that once per
:class:`ProjectIndex` and both rules read it.

Model, per class in the serving tier (``services/`` + ``cluster/`` +
``observability/``):

* **lock attributes** — ``self.X = threading.Lock()/RLock()/
  Condition()`` assignments (factory remembered: RLocks are reentrant,
  so re-acquisition is not a self-deadlock), plus any ``with self.X:``
  whose attribute name contains "lock" (a lock handed in from outside
  still declares the discipline).  A lock's identity is
  ``module.Class.attr`` — two instances of one class share the
  *ordering discipline* even though they hold distinct lock objects,
  which is exactly the granularity deadlock cycles care about.
* **attribute types** — ``self.x = SomeClass(...)`` in any method,
  with ``SomeClass`` resolved through the module's imports to a
  serving-tier class: ``self.x.m()`` then resolves to that class's
  method.
* **events per callable** — walking each method/function body with the
  lexical ``with``-held lock stack: lock acquisitions, recognized
  blocking operations, and calls (with their resolution) are recorded
  together with the locks held at that point.
* **summaries** — the set of locks a callable may acquire and the
  blocking operations it may perform, transitively through resolvable
  calls (memoized, cycle-guarded).  An edge ``A -> B`` exists when B
  is acquired (possibly deep in a callee) while A is held.

Approximations, all toward silence: calls on untyped objects
(parameters, container elements) are invisible; ``lock.acquire()``
call-form acquisition is not modeled (the repo uses ``with``);
aliased locks (``self._lock = other._lock``) are treated as distinct
identities.  Pure stdlib ``ast``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from znicz_tpu.analysis.project import module_name

SERVING_SCOPES = ("/services/", "/cluster/", "/observability/")

_LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
}

# recognized blocking operations, by fully-resolved dotted name
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep()",
    "urllib.request.urlopen": "urllib.request.urlopen()",
    "socket.create_connection": "socket.create_connection()",
    "subprocess.run": "subprocess.run()",
    "subprocess.call": "subprocess.call()",
    "subprocess.check_call": "subprocess.check_call()",
    "subprocess.check_output": "subprocess.check_output()",
    "os.system": "os.system()",
    "requests.get": "requests.get()",
    "requests.post": "requests.post()",
    "requests.put": "requests.put()",
    "requests.request": "requests.request()",
    "open": "open() file I/O",
    "jax.device_get": "jax.device_get() device sync",
    "jax.block_until_ready": "jax.block_until_ready() device sync",
}
# attribute calls that block regardless of arguments
_BLOCKING_ATTRS = {
    "block_until_ready": "device sync .block_until_ready()",
    "getresponse": "HTTP .getresponse()",
    "recv": "socket .recv()",
    "accept": "socket .accept()",
    "sendall": "socket .sendall()",
}
# attribute calls that block when spelled like a synchronization wait
# (ZNC010's homonym guard: zero positional args, non-module base).
# A timeout does NOT excuse these here — holding a lock across even a
# bounded wait stalls every thread needing the lock for that long.
_WAIT_ATTRS = {"get", "wait", "join"}


def in_serving_scope(info) -> bool:
    path = f"/{info.path}".replace("\\", "/")
    return any(scope in path for scope in SERVING_SCOPES)


def self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class LockAcq(NamedTuple):
    lock: str  # "module.Class.attr"
    node: ast.AST
    info: object  # ModuleInfo of the acquisition site
    via: str  # "" for direct, else the call chain that led here


class BlockOp(NamedTuple):
    desc: str
    node: ast.AST
    info: object
    via: str


class _Event(NamedTuple):
    kind: str  # "acquire" | "block" | "call"
    payload: object
    node: ast.AST
    held: Tuple[str, ...]


class _ClassInfo:
    __slots__ = (
        "info", "cls", "key", "lock_attrs", "lock_kind", "methods",
        "attr_types",
    )

    def __init__(self, info, cls: ast.ClassDef):
        self.info = info
        self.cls = cls
        self.key = f"{module_name(info.path)}.{cls.name}"
        self.methods: Dict[str, ast.AST] = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.lock_attrs: Set[str] = set()
        self.lock_kind: Dict[str, str] = {}
        self.attr_types: Dict[str, str] = {}  # attr -> resolved dotted
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                resolved = info.resolved(node.value.func)
                for t in node.targets:
                    attr = self_attr(t)
                    if attr is None:
                        continue
                    kind = _LOCK_FACTORIES.get(resolved or "")
                    if kind is not None:
                        self.lock_attrs.add(attr)
                        self.lock_kind[attr] = kind
                    elif resolved:
                        self.attr_types[attr] = resolved
            elif isinstance(node, ast.AnnAssign):
                # self.router: Router = router — an annotation types an
                # attribute the ctor receives instead of constructing
                attr = self_attr(node.target)
                ann = node.annotation
                if isinstance(ann, ast.Constant) and isinstance(
                    ann.value, str
                ):
                    dotted = ann.value
                elif isinstance(ann, (ast.Name, ast.Attribute)):
                    dotted = info.resolved(ann)
                else:
                    dotted = None
                if attr and dotted:
                    self.attr_types[attr] = dotted
            elif isinstance(node, ast.With):
                for item in node.items:
                    attr = self_attr(item.context_expr)
                    if attr and "lock" in attr.lower():
                        self.lock_attrs.add(attr)
                        self.lock_kind.setdefault(attr, "unknown")


class LockFlow:
    """The project's lock-order graph + blocking-under-lock events."""

    def __init__(self, index):
        self.index = index
        self._by_key: Dict[str, _ClassInfo] = {}
        for info in index.modules.values():
            if not in_serving_scope(info):
                continue
            for node in info.tree.body:
                if isinstance(node, ast.ClassDef):
                    ci = _ClassInfo(info, node)
                    self._by_key[ci.key] = ci
        self._events_memo: Dict[int, List[_Event]] = {}
        self._acq_memo: Dict[int, Dict[str, LockAcq]] = {}
        self._blk_memo: Dict[int, List[BlockOp]] = {}
        self._in_progress: Set[int] = set()
        # every (class, method) pair, for rule iteration
        self.all_methods: List[Tuple[_ClassInfo, str, ast.AST]] = [
            (ci, name, fn)
            for ci in self._by_key.values()
            for name, fn in ci.methods.items()
        ]

    # -- resolution ---------------------------------------------------------

    def _class_for(self, dotted: Optional[str]) -> Optional[_ClassInfo]:
        """A resolved constructor name -> serving-tier class.  Exact
        dotted key first, then a unique suffix match (``ClassB`` /
        ``registry.ReplicaRegistry`` spellings); an ambiguous short
        name resolves to nothing rather than guessing."""
        if not dotted:
            return None
        ci = self._by_key.get(dotted)
        if ci is not None:
            return ci
        matches = [
            c
            for key, c in self._by_key.items()
            if key.endswith("." + dotted)
        ]
        return matches[0] if len(matches) == 1 else None

    def _resolve_call(
        self, call: ast.Call, ci: Optional[_ClassInfo], info
    ):
        """-> ("unit", callable_node, owning info, label) or None."""
        func = call.func
        attr = self_attr(func)
        if attr is not None and ci is not None:
            fn = ci.methods.get(attr)
            if fn is not None:
                return (fn, info, f"self.{attr}()", ci)
            return None
        # self.x.m(): typed cross-object dispatch
        if (
            isinstance(func, ast.Attribute)
            and ci is not None
            and (base_attr := self_attr(func.value)) is not None
        ):
            dotted = ci.attr_types.get(base_attr)
            target = self._class_for(dotted)
            if target is not None:
                fn = target.methods.get(func.attr)
                if fn is not None:
                    return (
                        fn,
                        target.info,
                        f"self.{base_attr}.{func.attr}()",
                        target,
                    )
            return None
        # plain project function through the symbol table
        if isinstance(func, (ast.Name, ast.Attribute)):
            hit = self.index.resolve_symbol(info.resolved(func), home=info)
            if hit is not None and hit[1] is not None:
                tinfo, fn = hit
                label = info.dotted(func) or getattr(func, "attr", "?")
                return (fn, tinfo, f"{label}()", None)
        return None

    # -- event extraction ---------------------------------------------------

    def events(self, fn, ci: Optional[_ClassInfo], info) -> List[_Event]:
        key = id(fn)
        if key not in self._events_memo:
            out: List[_Event] = []
            self._walk(list(fn.body), (), ci, info, out)
            self._events_memo[key] = out
        return self._events_memo[key]

    def _lock_id(self, ci: Optional[_ClassInfo], attr: str) -> str:
        return f"{ci.key}.{attr}" if ci is not None else attr

    def _walk(self, body, held, ci, info, out) -> None:
        for node in body:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in node.items:
                    attr = self_attr(item.context_expr)
                    if (
                        attr is not None
                        and ci is not None
                        and attr in ci.lock_attrs
                    ):
                        lock = self._lock_id(ci, attr)
                        out.append(
                            _Event(
                                "acquire",
                                lock,
                                item.context_expr,
                                new_held,
                            )
                        )
                        new_held = new_held + (lock,)
                    else:
                        self._scan_exprs(
                            [item.context_expr], new_held, ci, info, out
                        )
                self._walk(node.body, new_held, ci, info, out)
                continue
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue  # nested defs run later, not under this lock
            children = list(ast.iter_child_nodes(node))
            # ExceptHandler/match_case are neither stmt nor expr but
            # CONTAIN statement bodies — route them through the
            # statement walk or error-path retry/backoff code (exactly
            # where sleep-under-lock lives) would go invisible
            stmt_like = (ast.stmt, ast.ExceptHandler, ast.match_case)
            stmt_children = [
                c for c in children if isinstance(c, stmt_like)
            ]
            expr_children = [
                c for c in children if not isinstance(c, stmt_like)
            ]
            self._scan_exprs(expr_children, held, ci, info, out)
            if stmt_children:
                self._walk(stmt_children, held, ci, info, out)

    def _scan_exprs(self, exprs, held, ci, info, out) -> None:
        stack = list(exprs)
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                # a with-statement nested inside an expression cannot
                # occur; guard anyway
                continue
            if isinstance(node, ast.Call):
                desc = self._blocking_desc(node, info)
                if desc is not None:
                    out.append(_Event("block", desc, node, held))
                else:
                    resolved = self._resolve_call(node, ci, info)
                    if resolved is not None:
                        out.append(_Event("call", resolved, node, held))
            stack.extend(ast.iter_child_nodes(node))

    def _blocking_desc(self, call: ast.Call, info) -> Optional[str]:
        resolved = info.resolved(call.func)
        if resolved in _BLOCKING_CALLS:
            return _BLOCKING_CALLS[resolved]
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        if attr in _BLOCKING_ATTRS:
            return _BLOCKING_ATTRS[attr]
        if attr in _WAIT_ATTRS and not call.args:
            base = call.func.value
            if isinstance(base, ast.Name) and (
                base.id in info.import_aliases
                or base.id in info.from_imports
            ):
                return None  # module-level homonym (os.wait())
            if self_attr(call.func) is not None:
                return None  # self.get()/self.join(): a method, not a wait
            return f"synchronization .{attr}() wait"
        return None

    # -- transitive summaries ----------------------------------------------

    def _owner_class(self, fn, info) -> Optional[_ClassInfo]:
        cur = info.parents.get(fn)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                key = f"{module_name(info.path)}.{cur.name}"
                return self._by_key.get(key)
            cur = info.parents.get(cur)
        return None

    def acquires(self, fn, ci, info, _depth=0) -> Dict[str, LockAcq]:
        """lock id -> one representative acquisition, transitively."""
        key = id(fn)
        if key in self._acq_memo:
            return self._acq_memo[key]
        if key in self._in_progress or _depth > 12:
            return {}
        self._in_progress.add(key)
        try:
            out: Dict[str, LockAcq] = {}
            for ev in self.events(fn, ci, info):
                if ev.kind == "acquire":
                    out.setdefault(
                        ev.payload, LockAcq(ev.payload, ev.node, info, "")
                    )
                elif ev.kind == "call":
                    cfn, cinfo, label, cci = ev.payload
                    if cci is None:
                        cci = self._owner_class(cfn, cinfo)
                    for lock, acq in self.acquires(
                        cfn, cci, cinfo, _depth + 1
                    ).items():
                        via = label if not acq.via else f"{label} -> {acq.via}"
                        out.setdefault(
                            lock, LockAcq(lock, ev.node, info, via)
                        )
        finally:
            self._in_progress.discard(key)
        self._acq_memo[key] = out
        return out

    def blocks(self, fn, ci, info, _depth=0) -> List[BlockOp]:
        """Recognized blocking operations reachable from ``fn``."""
        key = id(fn)
        if key in self._blk_memo:
            return self._blk_memo[key]
        if key in self._in_progress or _depth > 12:
            return []
        self._in_progress.add(key)
        try:
            out: List[BlockOp] = []
            for ev in self.events(fn, ci, info):
                if ev.kind == "block":
                    out.append(BlockOp(ev.payload, ev.node, info, ""))
                elif ev.kind == "call":
                    cfn, cinfo, label, cci = ev.payload
                    if cci is None:
                        cci = self._owner_class(cfn, cinfo)
                    for op in self.blocks(cfn, cci, cinfo, _depth + 1):
                        via = label if not op.via else f"{label} -> {op.via}"
                        out.append(BlockOp(op.desc, ev.node, info, via))
        finally:
            self._in_progress.discard(key)
        self._blk_memo[key] = out
        return out

    def lock_kind(self, lock_id: str) -> str:
        cls_key, _, attr = lock_id.rpartition(".")
        ci = self._by_key.get(cls_key)
        if ci is None:
            return "unknown"
        return ci.lock_kind.get(attr, "unknown")


def get_lockflow(index) -> LockFlow:
    lf = getattr(index, "_lockflow", None)
    if lf is None:
        lf = LockFlow(index)
        index._lockflow = lf
    return lf
