"""Incremental analysis cache: content-hash keyed per-file findings.

The tier-1 gate runs the whole-project analysis on every commit and
the ``--changed`` pre-push loop runs it on every edit; both pay the
full parse + rule cost even when almost nothing changed.  This module
makes the re-run cost proportional to the EDIT, not the repo, without
ever trading soundness for speed:

* **Fully warm** — the analyzer signature (analyzer version + the
  source hashes of every analysis module and registered rule + the
  mesh-axis declarations ZNC003 consults) and the per-file content
  manifest both match the cached run: the cached findings are returned
  verbatim, no parsing, no rules.  Well under a second.
* **Partially warm** — some files changed: the project index is still
  built over EVERYTHING (cross-module marks must stay correct — the
  ``--changed`` contract), but per-module rule execution is skipped
  for every unchanged file whose **cross-module marks digest** also
  matches.  The digest captures exactly what per-module rule output
  depends on beyond the file's own bytes: each def's traced mark and
  static-parameter set, and the chains anchored through the file
  (including the ENTRY file's content hash, since relocation copies
  the entry's symbol/snippet).  Project rules (dataflow, lock-order,
  blocking-under-lock) always re-run against the fresh index — their
  whole point is cross-module reasoning.

Per-file findings are stored keyed by the module that PRODUCED them
(post-suppression, post-relocation), so a chain finding re-anchored
into another file is reused/invalidated with its producer.  The cache
lives at ``tools/znicz_check_cache.json`` under the analysis root
(gitignored; a corrupt or version-skewed file is ignored, never
trusted), and the tier-1 gate asserts cold == warm equality so a
staleness bug is a test failure, not a silently green CI.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

from znicz_tpu.analysis.engine import (
    ANALYZER_VERSION,
    Finding,
    iter_py_files,
)
from znicz_tpu.analysis.project import (
    ProjectIndex,
    project_rule_findings,
)

CACHE_VERSION = 1
DEFAULT_CACHE_RELPATH = os.path.join("tools", "znicz_check_cache.json")


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def analyzer_signature(rules, root: str) -> str:
    """One hash over everything that can change finding semantics
    OTHER than the analyzed sources: analyzer version, the analysis
    engine's own source files, each active rule's module source, and
    the mesh-axis declarations ZNC003 reads from the analyzed tree."""
    h = hashlib.sha256()
    h.update(f"{ANALYZER_VERSION}:{CACHE_VERSION}:{root}".encode())
    files: List[str] = []
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    for d in (pkg_dir, os.path.join(pkg_dir, "rules")):
        for name in sorted(os.listdir(d)):
            if name.endswith(".py"):
                files.append(os.path.join(d, name))
    seen: Set[str] = set()
    for rule in sorted(rules, key=lambda r: r.id):
        h.update(rule.id.encode())
        mod = sys.modules.get(type(rule).__module__)
        f = getattr(mod, "__file__", None)
        if f and f not in seen:
            seen.add(f)
            files.append(f)
    for f in sorted(set(files)):
        try:
            with open(f, "rb") as fh:
                h.update(_sha(fh.read()).encode())
        except OSError:
            h.update(b"?")
    mesh = os.path.join(root, "znicz_tpu", "parallel", "mesh.py")
    if os.path.exists(mesh):
        with open(mesh, "rb") as fh:
            h.update(_sha(fh.read()).encode())
    return h.hexdigest()


def _marks_digest(info, index: ProjectIndex, manifest: Dict[str, str]) -> str:
    """Everything per-module rule output depends on beyond the file's
    own bytes: traced marks (local + cross-module) per def, and the
    chains whose helper lives here (with the ENTRY file's hash — the
    relocated finding copies the entry's symbol and snippet)."""
    marks = []
    for node in ast.walk(info.tree):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            traced = info.traced.is_traced(node)
            static = sorted(info.traced._static.get(node, ()))
            marks.append(
                [
                    getattr(node, "lineno", 0),
                    getattr(node, "col_offset", 0),
                    traced,
                    static,
                ]
            )
    chains = []
    for c in index._chains:
        if c.info is not info:
            continue
        entry_path = c.entry_info.path if c.entry_info else ""
        chains.append(
            [
                c.qual,
                list(c.chain),
                entry_path,
                getattr(c.entry_fn, "lineno", 0) if c.entry_fn else 0,
                manifest.get(entry_path, ""),
            ]
        )
    payload = json.dumps([marks, sorted(chains)], sort_keys=True)
    return _sha(payload.encode())


def _module_findings(info, index: ProjectIndex, rules) -> List[Finding]:
    """The per-module (non-project) rules over one cross-module-marked
    module, suppressed and relocated — the unit the cache stores."""
    out: List[Finding] = []
    for rule in rules:
        if getattr(rule, "project", False):
            continue
        for finding in rule.check(info):
            if not info.suppressed(finding):
                out.append(finding)
    return index.relocate(out)


def _dump(findings: Sequence[Finding]) -> List[Dict]:
    return [dataclasses.asdict(f) for f in findings]


def _load_findings(entries) -> List[Finding]:
    return [Finding(**e) for e in entries]


def load_cache(path: str) -> Optional[Dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    # znicz-check: disable=ZNC008 -- a missing/corrupt cache is the
    # defined cold path: the caller re-analyzes and rewrites it
    except (OSError, ValueError):  # znicz-check: disable=ZNC008
        return None
    if (
        not isinstance(data, dict)
        or data.get("cache_version") != CACHE_VERSION
    ):
        return None
    return data


def write_cache(path: str, data: Dict) -> None:
    """Best-effort atomic write — a read-only checkout just runs cold."""
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, path)
    # znicz-check: disable=ZNC008 -- best-effort by contract: a
    # read-only checkout (CI artifact dir) just runs cold next time
    except OSError:  # znicz-check: disable=ZNC008
        pass


def analyze_project_cached(
    paths: Sequence[str],
    *,
    root: Optional[str] = None,
    rules: Optional[Sequence] = None,
    report_paths: Optional[Set[str]] = None,
    cache_path: Optional[str] = None,
) -> Tuple[List[Finding], Optional[ProjectIndex], Dict]:
    """:func:`~znicz_tpu.analysis.project.analyze_project` with the
    incremental cache in front.  Returns ``(findings, index, stats)``
    — ``index`` is None on the fully-warm path (nothing was parsed),
    and ``stats`` reports ``{"mode": "cold"|"warm"|"partial",
    "reused": n, "analyzed": n}`` for the CLI summary line."""
    if rules is None:
        from znicz_tpu.analysis.rules import get_rules

        rules = get_rules()
    root = os.path.abspath(root or os.getcwd())
    if cache_path is None:
        cache_path = os.path.join(root, DEFAULT_CACHE_RELPATH)
    signature = analyzer_signature(rules, root)

    sources: Dict[str, str] = {}
    manifest: Dict[str, str] = {}
    for file in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(file), root).replace(
            os.sep, "/"
        )
        with open(file, "rb") as f:
            raw = f.read()
        manifest[rel] = _sha(raw)
        sources[rel] = raw.decode("utf-8")

    cached = load_cache(cache_path)
    if (
        cached is not None
        and cached.get("signature") == signature
        and cached.get("manifest") == manifest
    ):
        findings = _load_findings(cached.get("syntax", []))
        for entries in cached.get("per_file", {}).values():
            findings.extend(_load_findings(entries))
        findings.extend(_load_findings(cached.get("project", [])))
        if report_paths is not None:
            findings = [f for f in findings if f.path in report_paths]
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        stats = {
            "mode": "warm",
            "reused": len(manifest),
            "analyzed": 0,
        }
        return findings, None, stats

    index = ProjectIndex(root)
    for rel in sorted(sources):
        index.add_module(sources[rel], rel)
    index.link()

    old_manifest = (cached or {}).get("manifest", {})
    old_digests = (cached or {}).get("digests", {})
    old_per_file = (cached or {}).get("per_file", {})
    usable_cache = cached is not None and (
        cached.get("signature") == signature
    )

    per_file: Dict[str, List[Dict]] = {}
    digests: Dict[str, str] = {}
    reused = analyzed = 0
    findings: List[Finding] = list(index.syntax_findings)
    for rel, info in index.modules.items():
        digest = _marks_digest(info, index, manifest)
        digests[rel] = digest
        if (
            usable_cache
            and old_manifest.get(rel) == manifest[rel]
            and old_digests.get(rel) == digest
            and rel in old_per_file
        ):
            entries = old_per_file[rel]
            reused += 1
        else:
            entries = _dump(_module_findings(info, index, rules))
            analyzed += 1
        per_file[rel] = entries
        findings.extend(_load_findings(entries))

    project = project_rule_findings(index, rules)
    findings.extend(project)

    write_cache(
        cache_path,
        {
            "comment": (
                "znicz-check incremental analysis cache; safe to "
                "delete, never commit"
            ),
            "cache_version": CACHE_VERSION,
            "signature": signature,
            "manifest": manifest,
            "digests": digests,
            "per_file": per_file,
            "project": _dump(project),
            "syntax": _dump(index.syntax_findings),
        },
    )

    if report_paths is not None:
        findings = [f for f in findings if f.path in report_paths]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    stats = {
        "mode": "cold" if not usable_cache else "partial",
        "reused": reused,
        "analyzed": analyzed,
    }
    return findings, index, stats
