"""``python -m znicz_tpu`` — the reference's ``python3 -m veles`` entry point
(SURVEY.md 3.1)."""

import sys

from znicz_tpu.launcher import main

if __name__ == "__main__":
    sys.exit(main())
