"""Mesh construction + sharding helpers.

The mesh replaces the reference's master/slave process topology (SURVEY.md
3.4): axis ``data`` shards the batch (the reference's one parallelism
strategy, SURVEY.md 2.5), axis ``model`` optionally shards large layer
outputs (tensor parallelism — a new capability the reference lacks).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, model) mesh over the given (default: all) devices.

    ``n_data=None`` uses every remaining device on the data axis.  On real
    hardware callers should order devices so the model axis rides the
    fastest ICI links; here we take jax's default device order.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_model
    if n_data * n_model > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_model} needs {n_data * n_model} devices, "
            f"have {len(devices)}"
        )
    grid = np.array(devices[: n_data * n_model]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard dim 0 (batch) over ``data``; everything else replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_divisible(batch: int, mesh: Mesh) -> bool:
    return batch % mesh.shape[DATA_AXIS] == 0
