"""Mesh construction + sharding helpers.

The mesh replaces the reference's master/slave process topology (SURVEY.md
3.4): axis ``data`` shards the batch (the reference's one parallelism
strategy, SURVEY.md 2.5), axis ``model`` optionally shards large layer
outputs (tensor parallelism — a new capability the reference lacks).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    n_pipe: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, model[, pipe]) mesh over the given (default: all)
    devices.

    ``n_data=None`` uses every remaining device on the data axis.  The
    ``pipe`` axis only appears when ``n_pipe > 1`` (size-1 extra axes are
    harmless to GSPMD but noisy to read).  On real hardware callers should
    order devices so the model axis rides the fastest ICI links; here we
    take jax's default device order.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // (n_model * n_pipe)
    need = n_data * n_model * n_pipe
    if need > len(devices) or need < 1:
        raise ValueError(
            f"mesh {n_data}x{n_model}x{n_pipe} needs {need} devices, "
            f"have {len(devices)}"
        )
    if n_pipe > 1:
        grid = np.array(devices[:need]).reshape(n_data, n_model, n_pipe)
        return Mesh(grid, (DATA_AXIS, MODEL_AXIS, PIPE_AXIS))
    grid = np.array(devices[:need]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def parse_mesh_spec(spec: str) -> dict:
    """Parse the CLI/config mesh syntax ``"data=4,model=2,pipe=1"``.

    Axis names follow the framework's canonical mesh (SURVEY.md 3.4
    replacement): ``data`` shards batches, ``model`` shards weights
    (TP/EP), ``pipe`` shards pipeline stages.  Returns axis->size.
    """
    sizes = {}
    for part in spec.replace(" ", "").split(","):
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad mesh spec entry {part!r}: want axis=size "
                "(e.g. data=4,model=2)"
            )
        name, _, val = part.partition("=")
        if name not in (DATA_AXIS, MODEL_AXIS, PIPE_AXIS):
            raise ValueError(
                f"unknown mesh axis {name!r}: valid axes are "
                f"{DATA_AXIS}/{MODEL_AXIS}/{PIPE_AXIS}"
            )
        sizes[name] = int(val)
        if sizes[name] < 1:
            raise ValueError(f"mesh axis {name} must be >= 1")
    return sizes


def mesh_from_spec(
    spec: str, *, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """``"data=4,model=2"`` -> Mesh (unlisted axes default to 1; ``data``
    with no explicit size soaks up the remaining devices)."""
    sizes = parse_mesh_spec(spec)
    return make_mesh(
        sizes.get(DATA_AXIS),
        sizes.get(MODEL_AXIS, 1),
        sizes.get(PIPE_AXIS, 1),
        devices=devices,
    )


def data_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard dim 0 (batch) over ``data``; everything else replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_divisible(batch: int, mesh: Mesh) -> bool:
    return batch % mesh.shape[DATA_AXIS] == 0
