"""Mesh construction + sharding helpers.

The mesh replaces the reference's master/slave process topology (SURVEY.md
3.4): axis ``data`` shards the batch (the reference's one parallelism
strategy, SURVEY.md 2.5), axis ``model`` optionally shards large layer
outputs (tensor parallelism — a new capability the reference lacks).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    n_pipe: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, model[, pipe]) mesh over the given (default: all)
    devices.

    ``n_data=None`` uses every remaining device on the data axis.  The
    ``pipe`` axis only appears when ``n_pipe > 1`` (size-1 extra axes are
    harmless to GSPMD but noisy to read).  On real hardware callers should
    order devices so the model axis rides the fastest ICI links; here we
    take jax's default device order.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // (n_model * n_pipe)
    need = n_data * n_model * n_pipe
    if need > len(devices) or need < 1:
        raise ValueError(
            f"mesh {n_data}x{n_model}x{n_pipe} needs {need} devices, "
            f"have {len(devices)}"
        )
    if n_pipe > 1:
        grid = np.array(devices[:need]).reshape(n_data, n_model, n_pipe)
        return Mesh(grid, (DATA_AXIS, MODEL_AXIS, PIPE_AXIS))
    grid = np.array(devices[:need]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def parse_mesh_spec(spec: str) -> dict:
    """Parse the CLI/config mesh syntax ``"data=4,model=2,pipe=1"``.

    Axis names follow the framework's canonical mesh (SURVEY.md 3.4
    replacement): ``data`` shards batches, ``model`` shards weights
    (TP/EP), ``pipe`` shards pipeline stages.  Returns axis->size.
    """
    sizes = {}
    for part in spec.replace(" ", "").split(","):
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad mesh spec entry {part!r}: want axis=size "
                "(e.g. data=4,model=2)"
            )
        name, _, val = part.partition("=")
        if name not in (DATA_AXIS, MODEL_AXIS, PIPE_AXIS):
            raise ValueError(
                f"unknown mesh axis {name!r}: valid axes are "
                f"{DATA_AXIS}/{MODEL_AXIS}/{PIPE_AXIS}"
            )
        sizes[name] = int(val)
        if sizes[name] < 1:
            raise ValueError(f"mesh axis {name} must be >= 1")
    return sizes


def mesh_from_spec(
    spec: str, *, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """``"data=4,model=2"`` -> Mesh (unlisted axes default to 1; ``data``
    with no explicit size soaks up the remaining devices)."""
    sizes = parse_mesh_spec(spec)
    return make_mesh(
        sizes.get(DATA_AXIS),
        sizes.get(MODEL_AXIS, 1),
        sizes.get(PIPE_AXIS, 1),
        devices=devices,
    )


def verify_process_contiguous_data_axis(mesh: Mesh) -> None:
    """Check the multi-host row-ownership contract: each process's devices
    occupy one contiguous, process-pure block of the ``data`` axis, in
    process order.  ``Loader.set_process_shard`` serves process ``p`` rows
    ``[p*B/P, (p+1)*B/P)`` of every global minibatch, and
    ``DataParallel.shard_batch`` assembles them via
    ``jax.make_array_from_process_local_data`` — which places global row
    block ``d`` on ``mesh.devices[d]``.  A mesh whose device order
    interleaves processes would silently hand each process's rows different
    global positions than the loader contract states.  jax's default device
    order is process-contiguous, so this only trips hand-built meshes.
    """
    axes = list(mesh.axis_names)
    if DATA_AXIS not in axes:
        return
    dev = np.moveaxis(np.asarray(mesh.devices), axes.index(DATA_AXIS), 0)
    dev = dev.reshape(dev.shape[0], -1)  # 1-D (data-only) meshes included
    rows = [sorted({dv.process_index for dv in row}) for row in dev]
    procs = [r[0] for r in rows]
    counts = [procs.count(p) for p in sorted(set(procs))]
    if (
        any(len(r) != 1 for r in rows)
        or procs != sorted(procs)
        # the loader serves EQUAL 1/P row blocks, so unequal data-axis
        # shares violate the contract even when blocks are contiguous
        or len(set(counts)) > 1
    ):
        raise ValueError(
            "multi-host data axis does not give each process one equal "
            f"contiguous block: data-axis rows map to processes {rows}; "
            "order the mesh devices so every process owns "
            "n_data/n_processes consecutive rows (jax's default device "
            "order does this)"
        )


def data_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard dim 0 (batch) over ``data``; everything else replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_divisible(batch: int, mesh: Mesh) -> bool:
    return batch % mesh.shape[DATA_AXIS] == 0
