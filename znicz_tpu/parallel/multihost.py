"""Multi-host bring-up: the control-plane replacement for master/slave.

The reference's distributed story is a master process plus slave processes
over ZeroMQ (``--listen`` / ``--master-address``, SURVEY.md 3.4).  The
TPU-native equivalent is ``jax.distributed``: every host runs the SAME
program, a coordinator rendezvous wires them into one global device mesh, and
gradient exchange happens inside the jitted step via ICI/DCN collectives —
no tensor ever moves over the control plane.

On a multi-host pod slice (GKE/GCE TPU VMs) ``initialize()`` with no
arguments autodetects everything.  Off-pod (the reference's ad-hoc cluster
case) pass coordinator_address/num_processes/process_id explicitly — the
direct analogs of --listen / --master-address.
"""

from __future__ import annotations

from typing import Optional

from znicz_tpu.core.logger import setup_logging


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> dict:
    """Join (or create) the multi-host training job; returns topology info.

    Call before any other jax API.  After this, ``jax.devices()`` spans the
    whole job and ``parallel.make_mesh()`` builds global meshes.
    """
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    setup_logging()
    info = {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
    return info


def is_coordinator() -> bool:
    """True on exactly one process — gate snapshot writes and logging
    (the reference's 'master does the bookkeeping' role)."""
    import jax

    return jax.process_index() == 0


def process_count() -> int:
    """Number of controller processes in the job (1 = single-host)."""
    import jax

    return jax.process_count()


def process_index() -> int:
    import jax

    return jax.process_index()
