"""Data (+ optional tensor) parallel placement policy for workflows.

The TPU-native replacement for the reference's asynchronous parameter-server
DP (SURVEY.md 2.5 row "Data parallel"): the jitted train step runs SPMD over
the mesh; XLA turns the gradient contraction into an all-reduce over ICI.
Synchronous by construction — the convergence-relevant behavior
(every sample contributes once per epoch, one consistent model) matches the
reference's centralized aggregation.

Tensor parallelism (absent in the reference, SURVEY.md 2.5): FC/conv weights
whose output dim is divisible by the ``model`` axis and larger than
``tp_min_features`` are sharded on that dim; GSPMD propagates activations'
shardings and inserts the collectives.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from znicz_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    replicated,
)


def cnn_tp_rules(model, n_model: int, *, tp_min_features: int = 1024):
    """Channel-aware tensor-parallel placement for conv/FC models.

    Megatron-style alternation over the model's weighted layers: a layer
    whose output channels/features divide the ``model`` axis is COLUMN
    sharded (conv ``[ky, kx, in, out]`` on ``out``, FC ``[in, out]`` on
    ``out``, bias along); the NEXT weighted layer is ROW sharded on its
    input dim, so XLA contracts locally and psums partial products —
    conv kernels, the layers that dominate a CNN's FLOPs, stop
    replicating.  FC layers additionally honor ``tp_min_features`` (the
    size heuristic's threshold) so small heads stay replicated; conv
    layers shard on divisibility alone (their FLOPs justify it at any
    width).  Returns a ``param_rules`` callable for :class:`DataParallel`.
    """
    import re

    from znicz_tpu.parallel.mesh import MODEL_AXIS as M

    specs = {}
    col_prev = False
    for i, params in enumerate(model.params):
        w = params.get("weights") if isinstance(params, dict) else None
        if w is None or w.ndim < 2:
            continue
        is_conv = w.ndim == 4
        out_dim = w.shape[-1]
        in_dim = w.shape[-2] if is_conv else w.shape[0]
        if col_prev and is_conv and in_dim % n_model == 0:
            # row-parallel follower: shard the input/contraction dim.
            # Conv only — an FC after a flatten sees the channel-sharded
            # activations INTERLEAVED through its h*w*c input dim
            # (channel-minor flatten), so contiguous dim-0 weight sharding
            # would force a reshard instead of a local contract + psum
            specs[(i, "weights")] = P(None, None, M, None)
            specs[(i, "bias")] = P()
            col_prev = False
        elif out_dim % n_model == 0 and (
            is_conv or out_dim >= tp_min_features
        ):
            specs[(i, "weights")] = P(*([None] * (w.ndim - 1)), M)
            specs[(i, "bias")] = P(M)
            col_prev = True
        else:
            col_prev = False

    pat = re.compile(r"\[(\d+)\]\['(\w+)'\]")

    def rules(path: str, leaf):
        m = pat.search(path)
        if not m:
            return P()
        return specs.get((int(m.group(1)), m.group(2)), P())

    return rules


class DataParallel:
    """Placement policy: how batches and params land on the mesh.

    ``tp``: enable tensor-parallel weight sharding over the ``model`` axis.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        *,
        tp: bool = False,
        tp_min_features: int = 1024,
        param_rules=None,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        if jax.process_count() > 1:
            # the loader's per-process row contract only holds when each
            # process owns one contiguous block of the data axis
            from znicz_tpu.parallel.mesh import (
                verify_process_contiguous_data_axis,
            )

            verify_process_contiguous_data_axis(self.mesh)
        self.tp = tp and self.mesh.shape[MODEL_AXIS] > 1
        self.tp_min_features = tp_min_features
        # param_rules: callable (path_str, leaf) -> PartitionSpec or None.
        # Explicit model-aware placement (e.g. the transformer's QKV-head /
        # row-column FFN rules) — None falls through to the size heuristic.
        self.param_rules = param_rules

    @property
    def n_data(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    # -- batches -----------------------------------------------------------
    def shard_batch(self, arr, *, batch_dim: int = 0) -> jax.Array:
        """Place a host batch sharded over the data axis (the batch dim
        must divide by the axis size; the loader's padded static batches
        ensure a constant batch size, so pick minibatch_size accordingly).
        ``batch_dim=1`` serves epoch-stacked [n_steps, B, ...] payloads
        (the workflow's scanned dispatch).

        Multi-host (process_count > 1): ``arr`` is this process's LOCAL
        slice of the global batch — the loader's per-process shard contract
        (Loader.set_process_shard) serves each process rows
        ``[p*B/P, (p+1)*B/P)`` of every global minibatch, the same rows its
        addressable mesh devices own.  The pieces are assembled into ONE
        global array without any cross-host data movement (the reference's
        master never re-collected sample tensors either — SURVEY.md 3.4
        assigns index ranges to slaves)."""
        arr = np.asarray(arr)
        nproc = jax.process_count()
        if nproc > 1:
            gshape = list(arr.shape)
            gshape[batch_dim] *= nproc
            if gshape[batch_dim] % self.n_data:
                raise ValueError(
                    f"global batch {gshape[batch_dim]} not divisible by "
                    f"data axis {self.n_data}"
                )
            spec = [None] * arr.ndim
            spec[batch_dim] = DATA_AXIS
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, P(*spec)),
                arr,
                global_shape=tuple(gshape),
            )
        if arr.shape[batch_dim] % self.n_data:
            raise ValueError(
                f"batch {arr.shape[batch_dim]} not divisible by data axis "
                f"{self.n_data}; choose minibatch_size as a multiple"
            )
        spec = [None] * arr.ndim
        spec[batch_dim] = DATA_AXIS
        return jax.device_put(arr, NamedSharding(self.mesh, P(*spec)))

    def put_replicated(self, arr) -> jax.Array:
        """Place identical-on-every-process host data fully replicated over
        the mesh (epoch accumulators, loader device contexts) so jitted
        steps see consistently-placed global arrays on multi-host jobs."""
        return jax.device_put(arr, replicated(self.mesh))

    # -- params ------------------------------------------------------------
    def _param_spec(self, path: str, leaf) -> P:
        if self.param_rules is not None:
            spec = self.param_rules(path, leaf)
            if spec is not None:
                return spec
        if (
            self.tp
            and hasattr(leaf, "ndim")
            and leaf.ndim >= 1
            and leaf.shape[-1] >= self.tp_min_features
            and leaf.shape[-1] % self.mesh.shape[MODEL_AXIS] == 0
        ):
            # shard the output-features dim: column-parallel FC / conv
            return P(*([None] * (leaf.ndim - 1)), MODEL_AXIS)
        return P()

    def shard_state(self, state):
        """Place a TrainState: params/velocity per policy, scalars/key
        replicated.

        Leaves go device->host->mesh: a numpy source is the one input kind
        ``jax.device_put`` accepts for shardings that span non-addressable
        devices (multi-host), and every process holds the identical values
        (same seeds), so the host round-trip is also the correct global
        placement.  One-time cost at initialize, not in the hot loop."""
        import jax.numpy as jnp

        def put(leaf, sharding):
            if isinstance(leaf, jax.Array) and jnp.issubdtype(
                leaf.dtype, jax.dtypes.prng_key
            ):
                data = jax.device_put(
                    np.asarray(jax.random.key_data(leaf)), sharding
                )
                return jax.random.wrap_key_data(
                    data, impl=jax.random.key_impl(leaf)
                )
            return jax.device_put(np.asarray(leaf), sharding)

        def place(path, leaf):
            spec = self._param_spec(jax.tree_util.keystr(path), leaf)
            return put(leaf, NamedSharding(self.mesh, spec))

        params = jax.tree_util.tree_map_with_path(place, state.params)
        velocity = jax.tree_util.tree_map_with_path(place, state.velocity)
        rep = replicated(self.mesh)
        return state._replace(
            params=params,
            velocity=velocity,
            step=put(state.step, rep),
            key=put(state.key, rep),
        )
