"""Distribution layer: SPMD sharding over a device mesh.

Replaces the reference's entire L3 master-slave stack (``veles/server.py``,
``veles/client.py``, ZeroMQ+Twisted transport, ``IDistributable`` gradient
shipping — SURVEY.md 2.1, 2.5, 3.4): the batch is sharded over the mesh's
``data`` axis, parameters are replicated (or sharded over ``model`` for
tensor parallelism), and XLA emits the gradient all-reduce over ICI inside
the one jitted train step.  ``generate_data_for_slave`` / |
``apply_data_from_slave`` have no API equivalent — their observable behavior
(every device trains on its shard, one consistent model) is delivered by
construction, synchronously.

Elasticity contract (SURVEY.md 5.3): the reference's drop-slave/rejoin has no
SPMD equivalent; failure recovery is checkpoint-based restart via the
snapshotter.
"""

from znicz_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    data_sharding,
    make_mesh,
    mesh_from_spec,
    parse_mesh_spec,
    replicated,
)
from znicz_tpu.parallel.data_parallel import DataParallel  # noqa: F401
