"""Pipeline parallelism: GPipe-style microbatched stage pipeline.

NOT in the reference (SURVEY.md 2.5 lists pipeline parallel as absent) — a
new capability completing the DP/TP/SP set.  TPU-native formulation: S
identical-shaped stages are STACKED (params carry a leading stage dim) and
sharded over the mesh's ``pipe`` axis; activations flow through the ring
via ``ppermute`` while every device runs the same program (SPMD — no
per-stage programs, which is what makes this jit/XLA-friendly).

Memory is pipeline-grade, not correctness-grade (VERDICT r1 weak #4):
microbatch STORAGE is sharded over the pipe axis too — each device holds
``ceil(M/S)`` input and output microbatches, not the whole batch.  The
stores are circular conveyors: each tick exactly one input slot and one
output slot ppermute a hop backward (payload mb·F — the same size as the
activation hop), timed so stage 0 always finds its next microbatch
locally and finished chunks land chunk-per-device (``out_specs
P(pipe)``).

Schedule: at tick t (t = 0 .. S+M'-2, M' = S·ceil(M/S)), the device
holding stage s computes microbatch (t - s) when 0 <= t - s < M, then
activations rotate one hop forward.  The tick loop is one
``lax.fori_loop`` body — trace/compile cost independent of how many
microbatches you use to shrink the bubble — and autodiff through the
whole shard_map gives the backward pipeline for free (reverse ppermutes
appear in the transpose).  Bubble fraction is the GPipe (S-1)/(S-1+M') —
see :func:`bubble_fraction`.

Stages must share one signature/shape — the classic stacked-layer tower.
Embedding / head layers run outside the pipelined tower:
:func:`pipelined_model_apply` composes embed -> tower -> head.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from znicz_tpu.core.compat import pcast, shard_map
from znicz_tpu.parallel.mesh import PIPE_AXIS  # noqa: F401  (canonical axis)


def stack_stage_params(per_stage_params) -> Any:
    """[{...}, {...}, ...] (same shapes) -> one pytree with leading S dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params
    )


def bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Idle fraction of the GPipe schedule: (S-1)/(S-1+M') with M' the
    microbatch count padded up to a multiple of S.  Drive it down by
    raising ``n_microbatches``."""
    m_pad = n_stages * int(np.ceil(n_microbatches / n_stages))
    return (n_stages - 1) / (n_stages - 1 + m_pad)


def _local_pipeline(
    params, x, *, apply_one, axis_name, n_micro, n_stages, vary_axes=None
):
    """shard_map body: params [1, ...] (this device's stage), x [C, mb, F]
    (this device's CHUNK of the microbatch store, C = M'/S); returns this
    device's chunk of finished microbatches [C, mb, F].

    The stores are circular conveyors: every tick, exactly ONE input slot
    and one output slot rotate a hop backward (payload mb*F — the same
    size as the activation hop), timed so slot ``t % C`` of the input
    store holds global microbatch t on device 0 at tick t, and the last
    stage's finished chunk q lands on device q by the end.  One slot per
    tick keeps the whole schedule inside a single ``fori_loop`` body —
    trace/compile cost is O(1) in the microbatch count, not O(S + M)."""
    chunk = x.shape[0]
    if chunk * n_stages < n_micro:
        raise AssertionError(
            "per-device microbatch storage must be the padded chunk "
            f"ceil(M/S): got {chunk} for M={n_micro}, S={n_stages}"
        )
    s_idx = jax.lax.axis_index(axis_name)
    stage_params = jax.tree_util.tree_map(lambda p: p[0], params)
    m_pad = chunk * n_stages

    fwd = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    bwd = [(j, (j - 1) % n_stages) for j in range(n_stages)]

    # fresh constants are unvarying: pcast buf to varying over EVERY manual
    # axis (pipe, and data when composing with DP) before it mixes with
    # device-dependent values; zeros_like(x) inherits varying from x
    buf0 = pcast(
        jnp.zeros(x.shape[1:], x.dtype),
        vary_axes or axis_name,
        to="varying",
    )
    is_last = s_idx == n_stages - 1

    def _rotate_slot(store, slot, keep_old=None):
        cur = jax.lax.dynamic_index_in_dim(store, slot, keepdims=False)
        rot = jax.lax.ppermute(cur, axis_name, bwd)
        if keep_old is not None:
            rot = jnp.where(keep_old, cur, rot)
        return jax.lax.dynamic_update_index_in_dim(store, rot, slot, 0)

    def tick(t, carry):
        x_store, out_store, buf = carry
        s_in = jax.lax.rem(t, chunk)
        m = t - (n_stages - 1)  # microbatch the LAST stage finishes now
        s_out = jax.lax.rem(jnp.maximum(m, 0), chunk)
        # output conveyor rotates BEFORE the store below, so a finished
        # chunk q gets exactly S-1-q hops from the last stage -> device q
        out_store = _rotate_slot(out_store, s_out, keep_old=m < 0)
        # stage input: first stage reads its local store, others the ring
        micro_in = jax.lax.dynamic_index_in_dim(
            x_store, s_in, keepdims=False
        )
        stage_in = jnp.where(s_idx == 0, micro_in, buf)
        out = apply_one(stage_params, stage_in)
        active = (t - s_idx >= 0) & (t - s_idx < n_micro)
        out = jnp.where(active, out, buf)
        # last stage banks its finished microbatch into the conveyor
        cur = jax.lax.dynamic_index_in_dim(out_store, s_out, keepdims=False)
        banked = jnp.where(is_last & (m >= 0) & (m < n_micro), out, cur)
        out_store = jax.lax.dynamic_update_index_in_dim(
            out_store, banked, s_out, 0
        )
        buf = jax.lax.ppermute(out, axis_name, fwd)
        # input conveyor rotates AFTER device 0's read: slot s then holds
        # microbatch k*C+s on device 0 at tick k*C+s
        x_store = _rotate_slot(x_store, s_in)
        return x_store, out_store, buf

    _, out_local, _ = jax.lax.fori_loop(
        0, n_stages + m_pad - 1, tick, (x, jnp.zeros_like(x), buf0)
    )
    return out_local


def pipeline_apply(
    stacked_params,
    x: jnp.ndarray,
    *,
    apply_one: Callable,
    mesh: Mesh,
    n_microbatches: int,
    axis: str = PIPE_AXIS,
    data_axis: str = None,
    param_spec_fn=None,
    check_vma: bool = True,
) -> jnp.ndarray:
    """Run x [B, F] through the stacked stages, pipelined over ``mesh[axis]``.

    ``apply_one(stage_params, x_mb)`` applies ONE stage to one microbatch.
    B must divide by ``n_microbatches``.  Set ``check_vma=False`` only when
    ``apply_one`` contains pallas_calls (their out_shapes carry no
    varying-mesh-axes annotation) — it disables shard_map's safety check.

    ``data_axis``: compose with data parallelism — the per-microbatch row
    dim shards over that mesh axis, so each data replica runs its own
    pipeline over its batch shard (stage params replicate across ``data``;
    shard_map's transpose psums their grads over it automatically).  Real
    pipelines ride a (data, pipe) mesh — GPipe without DP is a demo.

    ``param_spec_fn``: optional ``(path_str, stacked_leaf) -> PartitionSpec``
    overriding the default P(pipe, None, ...) placement — the PPxTP hook:
    specs may shard weight dims over the ``model`` axis, in which case
    ``apply_one`` sees model-LOCAL stage weights and must contract locally
    + psum over that axis itself (Megatron row/column style).  Activations
    stay replicated over ``model``.
    """
    n_stages = mesh.shape[axis]
    if data_axis is not None:
        n_data = mesh.shape[data_axis]
        mb = x.shape[0] // n_microbatches
        if mb % n_data:
            raise ValueError(
                f"microbatch rows {mb} not divisible by data axis "
                f"{n_data} (batch {x.shape[0]}, M={n_microbatches})"
            )
    stage_dims = {
        leaf.shape[0] for leaf in jax.tree_util.tree_leaves(stacked_params)
    }
    if stage_dims != {n_stages}:
        raise ValueError(
            f"stacked params have stage dim(s) {sorted(stage_dims)} but "
            f"mesh axis {axis!r} has {n_stages} devices"
        )
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches {n_microbatches}"
        )
    micro = x.reshape((n_microbatches, b // n_microbatches) + x.shape[1:])
    # pad the microbatch store up to a multiple of S so each device holds
    # an equal chunk; padded microbatches are never computed or stored
    chunk = int(np.ceil(n_microbatches / n_stages))
    m_pad = chunk * n_stages
    if m_pad != n_microbatches:
        micro = jnp.concatenate(
            [micro, jnp.zeros((m_pad - n_microbatches,) + micro.shape[1:],
                              micro.dtype)]
        )

    def spec_for(leaf):
        return P(axis, *([None] * (leaf.ndim - 1)))

    if param_spec_fn is None:
        param_specs = jax.tree_util.tree_map(spec_for, stacked_params)
    else:
        param_specs = jax.tree_util.tree_map_with_path(
            lambda path, leaf: param_spec_fn(
                jax.tree_util.keystr(path), leaf
            ),
            stacked_params,
        )
    # microbatch STORE sharded chunk-per-device over pipe; under DP the
    # row dim additionally shards over data (independent pipeline per
    # data replica)
    store_spec = P(axis, data_axis)
    fn = shard_map(
        partial(
            _local_pipeline,
            apply_one=apply_one,
            axis_name=axis,
            n_micro=n_microbatches,
            n_stages=n_stages,
            vary_axes=(axis,) + ((data_axis,) if data_axis else ()),
        ),
        mesh=mesh,
        in_specs=(param_specs, store_spec),
        out_specs=store_spec,
        check_vma=check_vma,
    )
    out = fn(stacked_params, micro)[:n_microbatches]
    return out.reshape((b,) + out.shape[2:])


def pipelined_model_apply(
    params: Dict[str, Any],
    x: jnp.ndarray,
    *,
    embed_fn: Callable,
    stage_fn: Callable,
    head_fn: Callable,
    mesh: Mesh,
    n_microbatches: int,
    axis: str = PIPE_AXIS,
    data_axis: str = None,
    param_spec_fn=None,
    check_vma: bool = True,
) -> jnp.ndarray:
    """Embed -> pipelined tower -> head: the real-model decomposition
    (VERDICT r1 weak #4).  ``params`` = {"embed", "stages", "head"}; embed
    and head run outside the shard_map (replicated or whatever sharding
    GSPMD propagates), only the identically-shaped tower pipelines."""
    h = embed_fn(params["embed"], x)
    h = pipeline_apply(
        params["stages"], h,
        apply_one=stage_fn, mesh=mesh,
        n_microbatches=n_microbatches, axis=axis, data_axis=data_axis,
        param_spec_fn=param_spec_fn, check_vma=check_vma,
    )
    return head_fn(params["head"], h)


def shard_stacked_params(stacked_params, mesh: Mesh, axis: str = PIPE_AXIS):
    """Place stacked stage params with the stage dim sharded over ``axis``."""

    def place(leaf):
        spec = P(axis, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, stacked_params)
