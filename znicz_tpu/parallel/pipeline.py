"""Pipeline parallelism: GPipe-style microbatched stage pipeline.

NOT in the reference (SURVEY.md 2.5 lists pipeline parallel as absent) — a
new capability completing the DP/TP/SP set.  TPU-native formulation: S
identical-shaped stages are STACKED (params carry a leading stage dim) and
sharded over the mesh's ``pipe`` axis; microbatches flow through the ring
via ``ppermute`` while every device runs the same program (SPMD — no
per-stage programs, which is what makes this jit/XLA-friendly).

Schedule: at tick t (t = 0 .. S+M-2), the device holding stage s computes
microbatch (t - s) when 0 <= t - s < M, then activations rotate one hop
forward.  Autodiff through the whole shard_map gives the backward pipeline
for free (reverse ppermutes appear in the transpose).

Constraint: all stages share one signature/shape — the classic stacked-layer
pipeline (e.g. a tower of identical FC or transformer blocks).  Embedding /
head layers run outside the pipelined tower.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from znicz_tpu.parallel.mesh import PIPE_AXIS  # noqa: F401  (canonical axis)


def stack_stage_params(per_stage_params) -> Any:
    """[{...}, {...}, ...] (same shapes) -> one pytree with leading S dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params
    )


def _local_pipeline(params, x, *, apply_one, axis_name, n_micro):
    """shard_map body: params [1, ...] (this device's stage), x [M, mb, F]
    replicated microbatches; returns final activations [M, mb, F]."""
    s_idx = jax.lax.axis_index(axis_name)
    n_stages = jax.lax.psum(1, axis_name)
    stage_params = jax.tree_util.tree_map(lambda p: p[0], params)

    mb_shape = x.shape[1:]
    # each device's working buffer: current activation in flight
    def tick(t, carry):
        buf, outputs = carry
        my_micro = t - s_idx  # which microbatch this device would process
        active = (my_micro >= 0) & (my_micro < n_micro)
        # stage input: first stage reads the raw microbatch, others read buf
        micro_in = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(my_micro, 0, n_micro - 1), keepdims=False
        )
        stage_in = jnp.where(s_idx == 0, micro_in, buf)
        out = apply_one(stage_params, stage_in)
        out = jnp.where(active, out, buf)
        # last stage stores its finished microbatch
        is_last = s_idx == n_stages - 1
        store_idx = jnp.clip(my_micro, 0, n_micro - 1)
        outputs = jax.lax.cond(
            active & is_last,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, out, store_idx, axis=0
            ),
            lambda o: o,
            outputs,
        )
        # rotate activations one hop forward around the ring
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        buf = jax.lax.ppermute(out, axis_name, perm)
        return buf, outputs

    # pcast to varying: the loop mixes these with stage-dependent values
    def varying(v):
        return jax.lax.pcast(v, axis_name, to="varying")

    buf0 = varying(jnp.zeros(mb_shape, x.dtype))
    out0 = varying(jnp.zeros_like(x))
    _, outputs = jax.lax.fori_loop(
        0, n_stages + n_micro - 1, tick, (buf0, out0)
    )
    # every device returns the same [M, mb, F] buffer; only the last
    # stage's is filled — broadcast it back around the ring
    outputs = jax.lax.ppermute(
        outputs,
        axis_name,
        [(j, (j + 1) % n_stages) for j in range(n_stages)],
    )
    # after one hop, device 0 holds the last stage's outputs; psum-select
    outputs = jax.lax.psum(
        jnp.where(jax.lax.axis_index(axis_name) == 0, outputs, 0.0),
        axis_name,
    )
    return outputs


def pipeline_apply(
    stacked_params,
    x: jnp.ndarray,
    *,
    apply_one: Callable,
    mesh: Mesh,
    n_microbatches: int,
    axis: str = PIPE_AXIS,
) -> jnp.ndarray:
    """Run x [B, F] through the stacked stages, pipelined over ``mesh[axis]``.

    ``apply_one(stage_params, x_mb)`` applies ONE stage to one microbatch.
    B must divide by ``n_microbatches``.
    """
    n_stages = mesh.shape[axis]
    stage_dims = {
        leaf.shape[0] for leaf in jax.tree_util.tree_leaves(stacked_params)
    }
    if stage_dims != {n_stages}:
        raise ValueError(
            f"stacked params have stage dim(s) {sorted(stage_dims)} but "
            f"mesh axis {axis!r} has {n_stages} devices"
        )
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches {n_microbatches}"
        )
    micro = x.reshape((n_microbatches, b // n_microbatches) + x.shape[1:])

    def spec_for(leaf):
        return P(axis, *([None] * (leaf.ndim - 1)))

    param_specs = jax.tree_util.tree_map(spec_for, stacked_params)
    fn = jax.shard_map(
        partial(
            _local_pipeline,
            apply_one=apply_one,
            axis_name=axis,
            n_micro=n_microbatches,
        ),
        mesh=mesh,
        in_specs=(param_specs, P()),  # stages sharded; microbatches replicated
        out_specs=P(),
    )
    out = fn(stacked_params, micro)
    return out.reshape((b,) + out.shape[2:])


def shard_stacked_params(stacked_params, mesh: Mesh, axis: str = PIPE_AXIS):
    """Place stacked stage params with the stage dim sharded over ``axis``."""

    def place(leaf):
        spec = P(axis, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, stacked_params)
