"""Ring attention: sequence/context parallelism over the mesh.

Long-context first-class support: the sequence axis is sharded across
devices and K/V blocks rotate around the ring via ``ppermute`` over ICI while
each device's Q stays resident — attention over a sequence of length
``n_devices * T_local`` with per-device memory O(T_local^2) instead of
O(T^2).  Online-softmax (running max + normalizer) accumulation keeps the
result bit-comparable to single-device attention.

This is the blockwise/ring formulation (Liu et al.-style) expressed with
``shard_map`` + XLA collectives — the same mechanism that replaces the
reference's ZeroMQ data plane (SURVEY.md 2.5), applied to the sequence axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from znicz_tpu.core.compat import pcast, shard_map

SEQ_AXIS = "data"  # default: ring over the data axis of parallel.make_mesh


def _ring_body(i, carry, *, axis_name, scale, causal, t_local):
    o, m, l, k_blk, v_blk, q, my_idx = carry
    n = jax.lax.psum(1, axis_name)
    # blocks rotate j -> j+1 each step, so at step i device j holds the
    # block that originated at rank (j - i) mod n
    src = (my_idx - i) % n

    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
    ) * scale  # [B, H, Tq, Tk]
    if causal:
        q_pos = my_idx * t_local + jnp.arange(t_local)  # global q positions
        k_pos = src * t_local + jnp.arange(t_local)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)

    blk_max = jnp.max(s, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m, blk_max)
    # guard fully-masked blocks (all -inf rows)
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    correction = jnp.where(
        jnp.isneginf(m), 0.0, jnp.exp(m - m_safe)
    )  # rescale old accumulators
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )

    perm = [(j, (j + 1) % n) for j in range(n)]
    k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
    v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return (o_new, m_new, l_new, k_blk, v_blk, q, my_idx)


def _local_ring(q, k, v, *, axis_name, causal, scale):
    """Per-shard body under shard_map: q/k/v are the LOCAL sequence blocks
    [B, T_local, H, D]."""
    b, t_local, h, d = q.shape
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    # mark the fresh accumulators as device-varying so the fori_loop carry
    # types match after the body mixes them with sharded q/k/v
    def varying(x):
        return pcast(x, axis_name, to="varying")

    o = varying(jnp.zeros((b, h, t_local, d), jnp.float32))
    m = varying(jnp.full((b, h, t_local), -jnp.inf, jnp.float32))
    l = varying(jnp.zeros((b, h, t_local), jnp.float32))
    body = partial(
        _ring_body,
        axis_name=axis_name,
        scale=scale,
        causal=causal,
        t_local=t_local,
    )
    o, m, l, _, _, _, _ = jax.lax.fori_loop(
        0, n, body, (o, m, l, k, v, q, my_idx)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]  # [B, H, Tq, D]
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)  # [B, Tq, H, D]


def _flash_ring_body(i, carry, *, axis_name, scale, causal):
    """One ring step with the FLASH KERNEL as the inner block: the kernel's
    lse output lets normalized block results merge exactly —
    ``o = o*exp(lse_o - lse_new) + o_blk*exp(lse_blk - lse_new)``."""
    from znicz_tpu.ops.pallas.attention import flash_attention_lse

    o, lse, k_blk, v_blk, q, my_idx = carry
    n = jax.lax.psum(1, axis_name)
    src = (my_idx - i) % n

    def full_block(_):  # src < my: every key is in the past — no mask
        out, l = flash_attention_lse(
            q, k_blk, v_blk, causal=False, scale=scale
        )
        return out.astype(jnp.float32), l  # f32 like skip_block's zeros

    def diag_block(_):  # src == my: local causal == global causal
        out, l = flash_attention_lse(
            q, k_blk, v_blk, causal=True, scale=scale
        )
        return out.astype(jnp.float32), l

    def skip_block(_):  # src > my under causal: zero mass, and the switch
        # means the kernel never runs — the ring-level causal compute skip
        return jnp.zeros_like(o), jnp.full_like(lse, -1e30)

    if causal:
        branch = jnp.where(src < my_idx, 0, jnp.where(src == my_idx, 1, 2))
        o_blk, lse_blk = jax.lax.switch(
            branch, (full_block, diag_block, skip_block), None
        )
    else:
        o_blk, lse_blk = full_block(None)

    lse_new = jnp.logaddexp(lse, lse_blk)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_blk = jnp.exp(lse_blk - lse_new)[..., None]
    o = o * w_old + o_blk * w_blk

    perm = [(j, (j + 1) % n) for j in range(n)]
    k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
    v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return (o, lse_new, k_blk, v_blk, q, my_idx)


def _local_ring_flash(q, k, v, *, axis_name, causal, scale):
    """Per-shard body with flash-kernel inner blocks [B, T_local, H, D]."""
    b, t_local, h, d = q.shape
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)

    def varying(x):
        return pcast(x, axis_name, to="varying")

    o = varying(jnp.zeros((b, t_local, h, d), jnp.float32))
    lse = varying(jnp.full((b, t_local, h), -jnp.inf, jnp.float32))
    body = partial(
        _flash_ring_body, axis_name=axis_name, scale=scale, causal=causal
    )
    o, _, _, _, _, _ = jax.lax.fori_loop(
        0, n, body, (o, lse, k, v, q, my_idx)
    )
    return o.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    inner: str = "dense",  # "dense" (jnp blocks) | "flash" (pallas kernel)
) -> jnp.ndarray:
    """Attention with the sequence axis sharded over ``mesh[axis]``.

    ``q/k/v``: [B, T, H, D] global arrays (T divisible by the axis size).
    Returns [B, T, H, D] with the same sharding.  ``inner="flash"`` runs
    each per-shard block through the Pallas flash kernel (kernel-speed SP
    long context); the diagonal ring step reuses the kernel's causal path,
    fully-future blocks are skipped entirely via ``lax.switch``.
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if inner not in ("dense", "flash"):
        raise ValueError(f"inner={inner!r}: want 'dense' or 'flash'")
    local = _local_ring_flash if inner == "flash" else _local_ring
    spec = P(None, axis, None, None)
    fn = shard_map(
        partial(local, axis_name=axis, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # the pallas_call's out_shape carries no varying-axes annotation
        check_vma=inner != "flash",
    )
    return fn(q, k, v)
