"""Plotters: training-curve and weight visualizations.

Parity with ``veles/plotter.py``/``veles/plotting_units.py``
(AccumulatingPlotter) and ``znicz/nn_plotting_units.py`` (Weights2D)
[SURVEY.md 2.1, 2.3].  The reference ships plot state over ZMQ to a
GraphicsClient process; on a headless TPU host the idiomatic equivalent
renders PNGs (matplotlib Agg) and CSVs under an output directory after each
epoch — same information, no display server.

Each service implements ``on_epoch(workflow, verdict)``; the Workflow calls
every attached service at epoch end.
"""

from __future__ import annotations

import csv
import os
from typing import Optional

import numpy as np


class MetricsCSVWriter:
    """Append per-epoch metrics to metrics.csv (machine-readable history).

    Appending a run whose columns differ from an existing file's header
    rewrites the file with the merged header (absent values stay empty) —
    rows and header can never silently misalign.
    """

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, "metrics.csv")

    def on_epoch(self, workflow, verdict) -> None:
        summary = verdict["summary"]
        row = {"epoch": workflow.decision.epoch - 1}
        for split, m in summary.items():
            for key in ("loss", "n_err", "err_pct", "n_samples"):
                if key in m:
                    row[f"{split}_{key}"] = m[key]
        existing_rows: list = []
        fieldnames = list(row)
        if os.path.exists(self._path):
            with open(self._path, newline="") as f:
                reader = csv.DictReader(f)
                existing_rows = list(reader)
                old_fields = reader.fieldnames or []
            fieldnames = list(old_fields) + [
                k for k in row if k not in old_fields
            ]
        with open(self._path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=fieldnames, restval="")
            w.writeheader()
            for r in existing_rows:
                w.writerow(r)
            w.writerow(row)


class AccumulatingPlotter:
    """Error/loss curves across epochs -> PNG (reference AccumulatingPlotter)."""

    def __init__(
        self,
        directory: str,
        *,
        metric: str = "loss",
        filename: Optional[str] = None,
    ):
        self.directory = directory
        self.metric = metric
        self.filename = filename or f"{metric}.png"
        os.makedirs(directory, exist_ok=True)

    def on_epoch(self, workflow, verdict) -> None:
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        history = workflow.decision.history
        fig, ax = plt.subplots(figsize=(7, 4.5))
        for split in ("train", "valid", "test"):
            ys = [
                e[split].get(self.metric)
                for e in history
                if split in e and self.metric in e[split]
            ]
            if ys:
                ax.plot(range(len(ys)), ys, label=split, marker=".")
        ax.set_xlabel("epoch")
        ax.set_ylabel(self.metric)
        ax.set_title(f"{workflow.name}: {self.metric}")
        ax.legend()
        ax.grid(True, alpha=0.3)
        fig.tight_layout()
        fig.savefig(os.path.join(self.directory, self.filename), dpi=100)
        plt.close(fig)


class Weights2D:
    """First-layer weight tiles -> PNG (reference Weights2D).

    Works for FC weights reshaped to the input sample shape and for conv
    kernels [ky, kx, cin, cout].
    """

    def __init__(
        self,
        directory: str,
        *,
        layer: int = 0,
        max_tiles: int = 64,
        filename: Optional[str] = None,
    ):
        self.directory = directory
        self.layer = layer
        self.max_tiles = max_tiles
        self.filename = filename or f"weights{layer}.png"
        os.makedirs(directory, exist_ok=True)

    def _tiles(self, workflow) -> Optional[np.ndarray]:
        params = workflow.state.params
        layer_params = (
            params[self.layer] if isinstance(params, (list, tuple)) else params
        )
        w = layer_params.get("weights")
        if w is None:
            return None
        w = np.asarray(w)
        if w.ndim == 2:  # FC [in, out] -> tiles of the input shape
            sample = workflow.loader.sample_shape
            if int(np.prod(sample)) != w.shape[0]:
                return None
            side = sample if len(sample) >= 2 else None
            if side is None:
                n = int(np.sqrt(w.shape[0]))
                if n * n != w.shape[0]:
                    return None
                side = (n, n)
            return w.T.reshape((w.shape[1],) + tuple(side))[..., :, :]
        if w.ndim == 4:  # conv [ky, kx, cin, cout] -> per-kernel mean over cin
            return np.moveaxis(w.mean(axis=2), -1, 0)
        return None

    def on_epoch(self, workflow, verdict) -> None:
        tiles = self._tiles(workflow)
        if tiles is None:
            return
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        tiles = tiles[: self.max_tiles]
        if tiles.ndim == 4:  # drop trailing channel dims beyond 2D
            tiles = tiles.reshape(tiles.shape[0], tiles.shape[1], -1)
        n = len(tiles)
        cols = int(np.ceil(np.sqrt(n)))
        rows = int(np.ceil(n / cols))
        fig, axes = plt.subplots(rows, cols, figsize=(cols, rows))
        axes = np.atleast_1d(axes).ravel()
        for ax in axes:
            ax.axis("off")
        for i, tile in enumerate(tiles):
            axes[i].imshow(tile, cmap="gray")
        fig.suptitle(f"{workflow.name}: layer {self.layer} weights")
        fig.savefig(os.path.join(self.directory, self.filename), dpi=100)
        plt.close(fig)
