"""Status service: live training-state snapshot as JSON + HTML.

Parity with ``veles/web_status.py`` [SURVEY.md 2.1 "Web status"]: the
reference runs a tornado dashboard showing master/slaves/workflow progress.
Here the per-epoch state is written as ``status.json`` + a static
``status.html`` that auto-refreshes — servable by anything (``python -m
znicz_tpu.services.serve``), with no long-running service process coupled
to training.

Watch-while-training (the reference's live ZMQ plot rendering,
``veles/graphics_server.py``): point the plotters
(:mod:`znicz_tpu.services.plotting`) at the SAME directory and the status
page embeds every ``*.png`` it finds, cache-busted per refresh — error
curves and Weights2D tiles update live in the browser as epochs finish.
"""

from __future__ import annotations

import html
import json
import logging
import os

from znicz_tpu.observability import get_registry
from znicz_tpu.utils.profiling import Stopwatch

logger = logging.getLogger(__name__)


def _atomic_write(path: str, text: str) -> None:
    """Write-then-rename so a concurrently-polling reader (the serve
    process, a dashboard scraper) can never observe a truncated file.
    The temp file lives in the same directory, so ``os.replace`` is an
    atomic same-filesystem rename."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


class StatusWriter:
    """Per-epoch status/metrics files, optionally also PUSHED to a
    fleet :class:`~znicz_tpu.observability.aggregate.MetricsAggregator`
    (``aggregator_url``): the background pusher reports every
    ``push_interval_s`` and :meth:`on_epoch` flushes synchronously so
    the fleet view is epoch-fresh.  A dead aggregator costs log lines,
    never training time beyond the pusher's own bounded timeout."""

    def __init__(
        self,
        directory: str,
        *,
        refresh_seconds: int = 5,
        aggregator_url: str = None,
        instance: str = None,
        push_interval_s: float = 15.0,
    ):
        self.directory = directory
        self.refresh_seconds = refresh_seconds
        self._clock = Stopwatch()
        os.makedirs(directory, exist_ok=True)
        self._pusher = None
        if aggregator_url:
            from znicz_tpu.observability.aggregate import MetricsPusher

            self._pusher = MetricsPusher(
                aggregator_url,
                instance=instance or f"train-{os.getpid()}",
                interval_s=push_interval_s,
            ).start()

    def close(self) -> None:
        """Stop the aggregator pusher (final flush included)."""
        if self._pusher is not None:
            self._pusher.stop()

    def on_epoch(self, workflow, verdict) -> None:
        dec = workflow.decision
        status = {
            "workflow": workflow.name,
            "epoch": dec.epoch - 1,
            "max_epochs": dec.max_epochs,
            "best_value": dec.best_value,
            "best_epoch": dec.best_epoch,
            "improved": bool(verdict["improved"]),
            "stopping": bool(verdict["stop"]),
            "elapsed_seconds": round(self._clock.elapsed(), 1),
            "devices": self._devices(),
            "summary": verdict["summary"],
            "history_len": len(dec.history),
            # per-phase wall-clock ledger (reference per-unit timing on the
            # status page, SURVEY.md 5.1) — a windowed view over the same
            # registry histogram the full snapshot below exports
            "timing": (
                workflow.timer.summary()
                if getattr(workflow, "timer", None)
                else {}
            ),
            # the whole process-wide metrics registry, embedded so one
            # status.json answers "what is this process doing right now"
            "metrics": get_registry().snapshot(),
            # flight-recorder readout: the anomaly ring + active flag
            # (typed verdicts with last-K-steps snapshots) and the
            # input-pipeline attribution verdict — the same records
            # znicz-doctor derives from /metrics, epoch-fresh here
            "anomalies": self._anomalies(workflow),
            "pipeline": self._attribution(),
            # self-healing readout: rollback events/budget + lr backoff
            # (docs/TRAINING.md; restart counters ride "metrics")
            "recovery": self._recovery(workflow),
        }
        _atomic_write(
            os.path.join(self.directory, "status.json"),
            json.dumps(status, indent=2),
        )
        # Prometheus text beside the JSON: the serve process's /metrics
        # endpoint prefers this file (textfile-collector pattern), so a
        # scraper sees the TRAINING process's registry, not the server's
        _atomic_write(
            os.path.join(self.directory, "metrics.prom"),
            get_registry().prometheus_text(),
        )
        self._write_html(status)
        if self._pusher is not None:
            # epoch-fresh fleet view; bounded by the pusher's timeout
            self._pusher.push_now()

    @staticmethod
    def _anomalies(workflow) -> dict:
        """The workflow's flight-recorder report (empty when the
        detector is off).  Status must never break training."""
        detector = getattr(workflow, "anomaly", None)
        if detector is None:
            return {"active": False, "total": 0, "ring": []}
        try:
            return detector.report()
        except Exception:
            logger.debug("anomaly report failed", exc_info=True)
            return {"active": False, "total": 0, "ring": []}

    @staticmethod
    def _recovery(workflow) -> dict:
        """The workflow's recovery-policy readout (empty when no policy
        is wired).  Status must never break training."""
        policy = getattr(workflow, "recovery", None)
        if policy is None:
            return {"rollbacks_used": 0, "gave_up": False, "events": []}
        try:
            return policy.report()
        except Exception:
            logger.debug("recovery report failed", exc_info=True)
            return {"rollbacks_used": 0, "gave_up": False, "events": []}

    @staticmethod
    def _attribution() -> dict:
        """Pipeline-attribution verdict over the live registry (the
        ``{"type": "pipeline"}`` self-describing record)."""
        try:
            from znicz_tpu.observability.pipeline import (
                PipelineAttribution,
            )

            return PipelineAttribution.from_registry().attribution()
        except Exception:
            logger.debug("pipeline attribution failed", exc_info=True)
            return {"type": "pipeline", "verdict": "error"}

    @staticmethod
    def _devices():
        try:
            import jax

            return [str(d) for d in jax.devices()]
        except Exception:
            # status must never break training, but the degraded page
            # should be diagnosable
            logger.debug("device listing failed", exc_info=True)
            return []

    def _plot_images(self) -> list:
        """PNGs in the status directory (plotters writing alongside) with
        mtime cache-busters so the auto-refresh shows the newest frame."""
        out = []
        try:
            for name in sorted(os.listdir(self.directory)):
                if name.endswith(".png"):
                    mtime = int(
                        os.path.getmtime(os.path.join(self.directory, name))
                    )
                    out.append((name, mtime))
        except OSError:
            # a plotter writing concurrently can race the listing;
            # status must never break training, but leave a trace
            logger.debug(
                "plot image listing failed in %s",
                self.directory,
                exc_info=True,
            )
        return out

    @staticmethod
    def _doctor_html(status) -> str:
        """One-line doctor verdict + anomaly banner for the page."""
        lines = []
        pipe = status.get("pipeline") or {}
        if pipe.get("verdict") and pipe["verdict"] not in (
            "no-data", "error"
        ):
            fracs = pipe.get("fractions") or {}
            detail = ", ".join(
                f"{k} {v:.2f}" for k, v in fracs.items()
            )
            lines.append(
                f"<p>pipeline: <b>{html.escape(pipe['verdict'])}</b> "
                f"({html.escape(detail)})</p>"
            )
        anomalies = status.get("anomalies") or {}
        if anomalies.get("active"):
            counts = ", ".join(
                f"{k}={v}"
                for k, v in (anomalies.get("counts") or {}).items()
            )
            lines.append(
                '<p style="color:#b00"><b>anomaly active</b> '
                f"({html.escape(counts)})</p>"
            )
        return "\n".join(lines)

    def _write_html(self, status) -> None:
        rows = []
        for split, m in status["summary"].items():
            cells = "".join(
                f"<td>{html.escape(f'{v:.4f}' if isinstance(v, float) else str(v))}</td>"
                for v in (
                    m.get("n_samples", ""),
                    m.get("loss", ""),
                    m.get("err_pct", ""),
                )
            )
            rows.append(f"<tr><td>{html.escape(split)}</td>{cells}</tr>")
        doc = f"""<!DOCTYPE html>
<html><head><meta http-equiv="refresh" content="{self.refresh_seconds}">
<title>{html.escape(status['workflow'])}</title>
<style>body{{font-family:monospace;margin:2em}}table{{border-collapse:collapse}}
td,th{{border:1px solid #999;padding:4px 10px}}</style></head><body>
<h2>{html.escape(status['workflow'])}</h2>
<p>epoch {status['epoch']} / {status['max_epochs']} —
best {status['best_value']} @ {status['best_epoch']} —
{status['elapsed_seconds']}s elapsed</p>
<p>devices: {html.escape(', '.join(status['devices']))}</p>
{self._doctor_html(status)}
<table><tr><th>split</th><th>n</th><th>loss</th><th>err%</th></tr>
{''.join(rows)}</table>
{''.join(
    f'<p><img src="{html.escape(name)}?t={mtime}" '
    f'alt="{html.escape(name)}" style="max-width:45em"></p>'
    for name, mtime in self._plot_images()
)}
<details><summary>metrics registry snapshot</summary>
<pre>{html.escape(json.dumps(status.get("metrics", {}), indent=2))}</pre>
</details>
</body></html>"""
        _atomic_write(os.path.join(self.directory, "status.html"), doc)
