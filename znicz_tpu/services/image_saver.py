"""Image saver: dump worst/best classified samples per epoch.

Parity with ``znicz/image_saver.py`` [SURVEY.md 2.3 "Image saver"]: after an
evaluation pass, save the most-confidently-wrong and most-confidently-right
samples as PNGs (``<dir>/epoch<N>/{worst,best}_<rank>_t<truth>_p<pred>.png``).
Runs forward on the current params outside jit — it is a per-epoch service,
not hot-loop work.
"""

from __future__ import annotations

import os

import numpy as np


class ImageSaver:
    def __init__(
        self,
        directory: str,
        *,
        split: str = "test",
        n_images: int = 8,
        every_n_epochs: int = 1,
    ):
        self.directory = directory
        self.split = split
        self.n_images = n_images
        self.every_n_epochs = every_n_epochs
        os.makedirs(directory, exist_ok=True)

    def on_epoch(self, workflow, verdict) -> None:
        epoch = workflow.decision.epoch - 1
        if epoch % self.every_n_epochs:
            return
        model = workflow.model
        if not hasattr(model, "predict") or workflow.loss_function != "softmax":
            return
        import jax

        if jax.process_count() > 1:
            # services run on the coordinator only, but a single process can
            # neither run eager ops on globally-sharded params nor see the
            # other hosts' loader shards — a per-epoch sample dump is not
            # worth a collective, so the service declines once, loudly
            if not getattr(self, "_warned_multihost", False):
                self._warned_multihost = True
                workflow.warning(
                    "ImageSaver is disabled on multi-host runs (params span "
                    "hosts; each loader only serves its own shard)"
                )
            return
        xs, probs, labels = [], [], []
        # shuffle=False: a service pass must not advance the shuffle stream
        for mb in workflow.loader.batches(self.split, shuffle=False):
            p = np.asarray(model.predict(workflow.state.params, mb.data))
            valid = mb.mask > 0
            xs.append(np.asarray(mb.data)[valid])
            probs.append(p[valid])
            labels.append(mb.labels[valid])
        if not xs:
            return
        x = np.concatenate(xs)
        p = np.concatenate(probs)
        y = np.concatenate(labels)
        pred = p.argmax(axis=1)
        # host-only diagnostic fancy indexing over already-fetched
        # predictions; the array never feeds a compiled program
        conf = p[np.arange(len(p)), pred]  # znicz-check: disable=ZNC014
        wrong = pred != y
        out_dir = os.path.join(self.directory, f"epoch{epoch}")
        os.makedirs(out_dir, exist_ok=True)
        # worst: wrong with highest confidence; best: right with highest conf
        order_worst = np.argsort(-conf * wrong)[: self.n_images]
        order_best = np.argsort(-conf * ~wrong)[: self.n_images]
        for tag, order, keep in (
            ("worst", order_worst, wrong),
            ("best", order_best, ~wrong),
        ):
            for rank, i in enumerate(order):
                if not keep[i]:
                    continue
                self._save(
                    x[i],
                    os.path.join(
                        out_dir, f"{tag}_{rank}_t{y[i]}_p{pred[i]}.png"
                    ),
                )

    @staticmethod
    def _save(sample: np.ndarray, path: str) -> None:
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        img = np.asarray(sample)
        if img.ndim == 1:
            n = int(np.sqrt(img.size))
            if n * n != img.size:
                return
            img = img.reshape(n, n)
        if img.ndim == 3 and img.shape[-1] == 1:
            img = img[..., 0]
        fig, ax = plt.subplots(figsize=(2, 2))
        ax.imshow(img, cmap="gray" if img.ndim == 2 else None)
        ax.axis("off")
        fig.savefig(path, dpi=72, bbox_inches="tight")
        plt.close(fig)
