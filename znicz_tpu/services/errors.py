"""Typed serving errors — the submit-side half of the failure taxonomy.

Submit-time failures are EXCEPTIONS (the request never entered the
system); failures after acceptance are typed COMPLETIONS
(``Completion.finish_reason`` — see docs/SERVING.md "Failure
taxonomy").  A caller therefore handles exactly two shapes: an
exception at the door, or a completion with a reason.

:class:`RequestTooLargeError` subclasses ``ValueError`` so existing
callers that caught the engine's old bare ``ValueError`` keep working;
the message content (which names the backend's actual capacity) is
unchanged.
"""

from __future__ import annotations

import math
from typing import Optional


class RequestTooLargeError(ValueError):
    """The request can never fit this backend's KV capacity — no
    amount of queueing or retrying will help; shrink it or route it to
    a bigger pool."""


class SpeculationUnsupportedError(ValueError):
    """Speculative decoding was configured on a backend that cannot
    roll rejected tokens back — a CONFIG error, raised at engine
    construction, never per request.  Subclasses ``ValueError`` (the
    same contract as :class:`RequestTooLargeError`): callers that
    validate engine config with a bare ``except ValueError`` keep
    working, typed callers can route it specifically."""


class EngineClosedError(RuntimeError):
    """Submitted to a closed (or closing) front door / engine — the
    graceful-shutdown path; retry against a live replica."""


class RejectedError(RuntimeError):
    """Load shed at admission: the pending queue or the KV pool crossed
    its watermark.  TRANSIENT — retry after ``retry_after_s``; the HTTP
    surface maps this to ``503`` + ``Retry-After``."""

    def __init__(
        self,
        message: str,
        *,
        reason: str = "queue_full",
        retry_after_s: float = 1.0,
    ):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = float(retry_after_s)


def retryable(exc: BaseException) -> Optional[float]:
    """Seconds to wait before retrying ``exc``, or None when the error
    is permanent (too large, malformed)."""
    if isinstance(exc, RejectedError):
        return exc.retry_after_s
    if isinstance(exc, EngineClosedError):
        return 1.0
    return None


def retry_after_header(exc: BaseException) -> str:
    """``Retry-After`` header value for a retryable error: whole
    seconds, rounded up, floored at 1.  ONE owner of the clamping
    rule, shared by the replica HTTP surface and the cluster router's
    — the two must never advertise different backoff for the same
    rejection."""
    return str(max(int(math.ceil(retryable(exc) or 1.0)), 1))
