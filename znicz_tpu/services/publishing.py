"""Run-report publishing.

Capability parity with ``veles/publishing/`` [SURVEY.md 2.1 "Publishing"]:
generate a run report when training finishes.  The reference renders to
external sinks (wiki/confluence backends); here the sink is a Markdown file
(the universally consumable format) containing config, per-epoch metrics and
the outcome — attach as an epoch service, it writes on the stopping epoch.
"""

from __future__ import annotations

import json
import os
import time

from znicz_tpu.utils.profiling import Stopwatch


class MarkdownReporter:
    def __init__(self, directory: str, *, filename: str = "report.md"):
        self.directory = directory
        self.filename = filename
        self._clock = Stopwatch()
        os.makedirs(directory, exist_ok=True)

    def on_epoch(self, workflow, verdict) -> None:
        if not verdict["stop"]:
            return
        dec = workflow.decision
        lines = [
            f"# Run report: {workflow.name}",
            "",
            f"- finished: {time.strftime('%Y-%m-%d %H:%M:%S')}",
            f"- wall time: {self._clock.elapsed():.1f}s",
            f"- epochs: {dec.epoch}",
            f"- best value: {dec.best_value} (epoch {dec.best_epoch})",
            f"- loss function: {workflow.loss_function}",
            "",
            "## Model",
            "",
        ]
        model = workflow.model
        if getattr(model, "layer_types", None):
            lines.append("| # | layer | params |")
            lines.append("|---|-------|--------|")
            for i, (t, p) in enumerate(zip(model.layer_types, model.params)):
                shapes = ", ".join(
                    f"{k}{list(v.shape)}" for k, v in p.items()
                ) or "—"
                lines.append(f"| {i} | {t} | {shapes} |")
        lines += ["", "## Epoch history", ""]
        header_written = False
        for epoch, summary in enumerate(dec.history):
            cols = []
            for split in ("train", "valid", "test"):
                if split in summary:
                    m = summary[split]
                    cols.append(
                        f"{m['loss']:.5f}"
                        + (
                            f" / {m['err_pct']:.2f}%"
                            if m.get("n_err") is not None
                            and workflow.loss_function == "softmax"
                            else ""
                        )
                    )
                else:
                    cols.append("—")
            if not header_written:
                lines.append("| epoch | train | valid | test |")
                lines.append("|---|---|---|---|")
                header_written = True
            lines.append(f"| {epoch} | {cols[0]} | {cols[1]} | {cols[2]} |")
        path = os.path.join(self.directory, self.filename)
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        # machine-readable twin
        with open(os.path.join(self.directory, "report.json"), "w") as f:
            json.dump(
                {
                    "workflow": workflow.name,
                    "epochs": dec.epoch,
                    "best_value": dec.best_value,
                    "best_epoch": dec.best_epoch,
                    "history": dec.history,
                },
                f,
                indent=2,
                default=str,
            )
