"""Continuous micro-batching decode engine: the LM serving front-end.

Orca-style continuous batching (PAPERS.md lineage) over the bucketed
decode fast path (:mod:`znicz_tpu.workflow.generate`, docs/SERVING.md):
a request queue coalesces pending prompts into a fixed B-slot batch over
STATIC [B, T_max] KV buffers; when a row retires (EOS or budget), its
slot is re-used by prefilling the next queued prompt into it while the
other rows keep decoding.  Two compiled programs cover any request
stream:

* **admit** — prefill ONE left-padded [1, bucket] prompt into a fresh
  zeroed cache row and scatter it into the batch at the slot index; one
  compile per prompt-length bucket (geometric ladder, so a handful).
* **decode chunk** — up to ``admit_every`` incremental steps for the
  whole batch in one ``lax.while_loop`` (early exit once every row is
  done), with PER-ROW positions (the cache write is vmapped into a
  scatter), so rows at different depths decode together and no prompt
  length or admission pattern ever recompiles it.

**Paged backend** (:class:`PagedDecodeEngine`, PAPERS.md vLLM/Sarathi/
RadixAttention lineage): instead of a dense ``[B, T_max]`` reservation
per slot, K/V live in a shared block pool (``[n_blocks, block_size, H,
hd]`` per layer) and each slot owns a block table over REFCOUNTED
blocks.  Admission maps the longest prefix of the prompt already in the
content-hash PREFIX CACHE (chained block hashes — an implicit radix
structure; retiring and preempted requests publish their completed full
blocks) and chunk-prefills only the uncached tail; shared blocks are
read-only behind a copy-on-write guard.  Blocks are otherwise allocated
lazily as decode advances, prompts prefill in block-sized CHUNKS
interleaved with decode chunks (a long prompt never stalls the batch),
and when the free list runs dry allocation first EVICTS cache-only
blocks (LRU) and only then PREEMPTS the youngest request — publishes +
releases its blocks, requeues it for recompute-on-readmission — instead
of rejecting.  Concurrency is bounded by memory actually used, not by
``n_slots * T_max`` worst case; docs/SERVING.md has the tuning table.

Telemetry rides :mod:`znicz_tpu.observability`: admissions, retirements
(by reason), generated tokens and per-(kind, bucket) compiles are
registry counters; queue depth and active slots are gauges; per-request
latency and time-to-first-token are histograms — all visible on
``/metrics`` and in ``status.json``.  Per-instance views stay available
(``latency`` is a bounded :class:`~znicz_tpu.utils.profiling.LatencyStats`
window feeding the shared latency histogram; ``timer`` is a
:class:`~znicz_tpu.observability.PhaseTimer` whose admit/decode phases
also emit tracer spans — one ``serve/admit`` span per request), and
compile counts are introspectable via
:meth:`DecodeEngine.compile_stats`.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from collections import OrderedDict, deque
from functools import partial
from typing import Deque, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu import observability
from znicz_tpu.observability import device as device_telemetry
from znicz_tpu.services.errors import (
    RequestTooLargeError,
    SpeculationUnsupportedError,
)
from znicz_tpu.utils import faults, profiling
from znicz_tpu.workflow.generate import (
    DEFAULT_PROMPT_BUCKETS,
    DEFAULT_SPEC_BUCKETS,
    NULL_BLOCK,
    PromptLookupDrafter,
    _check_sampling_args,
    _filter_logits,
    _params_fingerprint,
    _sample,
    bucket_for,
    copy_paged_block,
    decode_step,
    init_kv_cache,
    init_paged_kv,
    pack_prompts,
    paged_decode_step,
    paged_prefill_chunk,
    paged_verify_chunk,
    prefill,
)

# process-wide first-compile ledger backing znicz_serve_compiles_total:
# the jit caches are shared across engines, so a second engine with the
# same (params geometry, program key) compiles NOTHING new and must not
# re-increment the counter.  (jax.clear_caches() invalidates this — the
# counter then under-reports the recompiles; acceptable for a process-
# lifetime first-compile metric.)
_COMPILED_KEYS: set = set()

# seed of the prefix-cache hash chain (versioned: bump if block content
# semantics ever change, so stale-looking hashes can't alias)
_PREFIX_SEED = b"znicz-prefix-v1"


def _chain_digests(tokens: np.ndarray, block_size: int):
    """Chained sha256 over full ``block_size``-token blocks of
    ``tokens``: block j's key commits to ALL tokens before it, so equal
    keys mean equal K/V content, and walking the chain until the first
    miss is the longest-cached-prefix descent of an implicit radix
    structure.  The ONE owner of the keying scheme — the engine's
    prefix cache and the cluster router's affinity index both hash
    through here, so their keys can never drift apart."""
    h = _PREFIX_SEED
    for j in range(tokens.size // block_size):
        h = hashlib.sha256(
            h
            + np.ascontiguousarray(
                tokens[j * block_size:(j + 1) * block_size]
            ).tobytes()
        ).digest()
        yield h


def prefix_block_keys(prompt, block_size: int) -> List[str]:
    """Public prefix-cache block keys for ``prompt`` (hex, full blocks
    only) — the routing key a :class:`~znicz_tpu.cluster.router
    .ServingRouter` indexes replicas by, and what
    :meth:`DecodeEngine.prefix_probe` returns.  Pure function of the
    token content (prompts are hashed as int32, matching the engine's
    internal chain), independent of any live engine state."""
    p = np.asarray(prompt, np.int32).reshape(-1)
    return [h.hex() for h in _chain_digests(p, int(block_size))]


@dataclasses.dataclass
class RequestTimings:
    """Per-request lifecycle breakdown — the answer to "why was this
    request slow", attached to every :class:`Completion` (and the HTTP
    done record).  All host wall-clock (``time.perf_counter`` deltas):

    * ``queue_s`` — time spent WAITING (engine queue before first
      admission, plus every re-queue wait after a preemption; the
      front door adds its own pending-queue wait on top).
    * ``prefill_s`` — wall time of this request's own admit/prefill
      program calls (per-chunk on the paged backend).
    * ``decode_s`` — wall time of the decode chunks this request was
      RESIDENT in.  Chunks are batched, so concurrent residents each
      count the full chunk — a per-request share of shared tower work,
      not a sum that totals to wall time across requests.
    * ``preemptions`` — times this request was evicted and recomputed.
    * ``cached_tokens`` — prompt tokens whose prefill was skipped via
      the prefix cache (accumulated across re-admissions).
    * ``spec_drafted`` / ``spec_accepted`` — draft tokens proposed for
      (and accepted by) this request's speculative verify steps; their
      ratio is the per-request acceptance rate, the number that says
      whether speculation paid for THIS request.
    """

    queue_s: float = 0.0
    prefill_s: float = 0.0
    decode_s: float = 0.0
    preemptions: int = 0
    cached_tokens: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0

    def as_dict(self) -> Dict:
        return {
            "queue_s": round(self.queue_s, 6),
            "prefill_s": round(self.prefill_s, 6),
            "decode_s": round(self.decode_s, 6),
            "preemptions": self.preemptions,
            "cached_tokens": self.cached_tokens,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
        }


@dataclasses.dataclass
class Request:
    """One queued generation request: a 1-D prompt with its own budget."""

    id: int
    prompt: np.ndarray  # 1-D int32
    max_new_tokens: int
    bucket: int  # prompt-length bucket it will be admitted at
    watch: profiling.Stopwatch  # started at submit; read at retirement
    ttft_s: Optional[float] = None  # set once at FIRST admission
    # memoized prefix-cache hash chain (pure function of the prompt —
    # computed once per request; block RESOLUTION stays per-tick fresh)
    digests: Optional[List[bytes]] = None
    # end-to-end tracing: the client-visible id (set by the front door)
    # and the lifecycle breakdown this request accumulates
    trace_id: Optional[str] = None
    timings: RequestTimings = dataclasses.field(
        default_factory=RequestTimings
    )
    # watch-relative instant this request last (re-)entered the queue:
    # 0.0 at submit, bumped at preemption — queue_s accrues from here
    last_queued_at: float = 0.0


@dataclasses.dataclass
class Completion:
    """A finished request: prompt + generated tokens plus its serving
    metrics.  ``latency_s`` is submit -> retirement (queue wait
    included — the number a caller actually experiences); ``ttft_s`` is
    submit -> first sampled token.

    ``finish_reason`` is the full failure taxonomy (docs/SERVING.md):
    ``"eos"`` / ``"budget"`` from the engine itself, plus the typed
    terminations the front door retires with — ``"cancelled"``,
    ``"deadline_exceeded"``, ``"error"`` (engine-thread failure;
    ``error`` carries the message) and ``"shed"`` (dropped at
    shutdown).  ``trace_id`` is the client-visible request id when the
    request came through a :class:`~znicz_tpu.services.frontdoor
    .ServingFrontDoor`."""

    id: int
    tokens: np.ndarray  # prompt + generated, EOS included when hit
    n_new: int
    finish_reason: str  # "eos" | "budget" | typed front-door reasons
    latency_s: float
    tokens_per_sec: float
    bucket: int
    ttft_s: Optional[float] = None
    error: Optional[str] = None  # set for finish_reason == "error"
    trace_id: Optional[str] = None  # front-door request id
    # per-request lifecycle breakdown (RequestTimings.as_dict():
    # queue_s / prefill_s / decode_s / preemptions / cached_tokens)
    timings: Optional[Dict] = None


def _sample_tok(logits, key, temperature, top_p, *, greedy, top_k, nucleus):
    """Engine twin of the generate() sampler: greedy argmax or the
    shared truncated-softmax ``_sample`` (structural knobs static)."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return _sample(logits, key, temperature, top_k, nucleus, top_p)


@partial(
    jax.jit,
    static_argnames=(
        "n_heads", "greedy", "top_k", "nucleus", "moe_top_k",
        "moe_dispatch",
    ),
    donate_argnums=(1,),
)
def _admit_row(
    params, caches, prompt, start, slot, temperature, top_p, key, *,
    n_heads, greedy, top_k, nucleus, moe_top_k, moe_dispatch,
):
    """Prefill ONE left-padded [1, bucket] prompt into row ``slot`` of
    the batch caches and sample its first token.

    The row is rebuilt from a fresh ZEROED [1, T_max] cache, so the
    previous occupant's K/V cannot leak into the new request (causality
    already guarantees it — a query at position q only attends
    positions <= q, all rewritten by the current occupant — the zeroed
    row makes it true by construction too).  Compiles once per prompt
    bucket (shape-keyed); the slot index is a traced operand."""
    t_max = caches[0]["k"].shape[1]
    row = init_kv_cache(params, 1, t_max, n_heads=n_heads)
    row, logits = prefill(
        params, prompt, row, n_heads=n_heads, start=start,
        moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
    )
    new = []
    for big, r in zip(caches, row):
        new.append(
            {
                "k": jax.lax.dynamic_update_slice(
                    big["k"], r["k"], (slot, 0, 0, 0)
                ),
                "v": jax.lax.dynamic_update_slice(
                    big["v"], r["v"], (slot, 0, 0, 0)
                ),
            }
        )
    first = _sample_tok(
        logits, key, temperature, top_p, greedy=greedy, top_k=top_k,
        nucleus=nucleus,
    )
    return new, first[0]


@partial(
    jax.jit,
    static_argnames=(
        "chunk", "n_heads", "eos_id", "greedy", "top_k", "nucleus",
        "moe_top_k", "moe_dispatch",
    ),
    donate_argnums=(1,),
)
def _decode_chunk(
    params, caches, tok, pos, start, done, remaining, temperature,
    top_p, rng, *, chunk, n_heads, eos_id, greedy, top_k, nucleus,
    moe_top_k, moe_dispatch,
):
    """Up to ``chunk`` decode steps for the whole batch in ONE compiled
    program, exiting early once every row is done.

    Positions are PER-ROW — the cache write is vmapped into a scatter —
    so rows admitted at different times (different prompt lengths,
    different depths) decode together, and NO prompt length or admission
    pattern ever recompiles this program: the zero-recompile core of the
    engine.  Rows already done emit ``eos_id`` and idle in place (their
    clamped cache write is dead — the slot is rebuilt at re-admission).

    Returns (caches, tok, pos, done, remaining, out [B, chunk], steps):
    the host reads ``out[:, :steps]`` to collect emissions and retire
    rows."""
    b = tok.shape[0]
    t_max = caches[0]["k"].shape[1]
    fill = jnp.int32(eos_id)
    out = jnp.full((b, chunk), fill, jnp.int32)

    def step_rows(caches, tok, pos):
        def one(cache_row, t, p, s):
            c1 = jax.tree_util.tree_map(lambda a: a[None], cache_row)
            c2, lg = decode_step(
                params, c1, t[None], p, n_heads=n_heads, start=s[None],
                moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
            )
            return jax.tree_util.tree_map(lambda a: a[0], c2), lg[0]

        return jax.vmap(one)(caches, tok, pos, start)

    def cond(carry):
        i, _, _, _, done, _, _ = carry
        return (i < chunk) & ~jnp.all(done)

    def body(carry):
        i, caches, tok, pos, done, remaining, out = carry
        caches, logits = step_rows(caches, tok, pos)
        nxt = _sample_tok(
            logits, jax.random.fold_in(rng, i), temperature, top_p,
            greedy=greedy, top_k=top_k, nucleus=nucleus,
        )
        nxt = jnp.where(done, fill, nxt)
        remaining = jnp.where(done, remaining, remaining - 1)
        done = done | (nxt == eos_id) | (remaining <= 0)
        out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, i))
        pos = jnp.minimum(pos + 1, t_max - 1)
        return (i + 1, caches, nxt, pos, done, remaining, out)

    i, caches, tok, pos, done, remaining, out = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), caches, tok, pos, done, remaining, out),
    )
    return caches, tok, pos, done, remaining, out, i


@partial(
    jax.jit,
    static_argnames=(
        "block_size", "n_heads", "greedy", "top_k", "nucleus",
        "moe_top_k", "moe_dispatch",
    ),
    donate_argnums=(1,),
)
def _paged_prefill_prog(
    params, pools, table, tokens, offset, start, last, temperature,
    top_p, key, *, block_size, n_heads, greedy, top_k, nucleus,
    moe_top_k, moe_dispatch,
):
    """One aligned prompt chunk into the row's blocks + first-token
    sample.  ONE compiled shape covers every prompt length and every
    chunk index (``offset``/``table``/``last`` are traced operands; the
    chunk is always ``[1, block_size]``) — chunked prefill's compile
    story beats the dense path's one-admit-program-per-bucket.  ``last``
    is the in-chunk index of the prompt's final real token (the tail of
    the final chunk is RIGHT-pad — prefix-cache alignment); the sample
    only matters on the final chunk; computing it unconditionally keeps
    the program single and costs one argmax/categorical per chunk."""
    pools, logits = paged_prefill_chunk(
        params, pools, table, tokens, offset, n_heads=n_heads,
        block_size=block_size, start=start, last=last,
        moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
    )
    first = _sample_tok(
        logits, key, temperature, top_p, greedy=greedy, top_k=top_k,
        nucleus=nucleus,
    )
    return pools, first[0]


@partial(jax.jit, donate_argnums=(0,))
def _cow_copy_prog(pools, src, dst):
    """Copy-on-write block split (:func:`copy_paged_block` with the
    pools donated): ``src``/``dst`` are traced, so one compiled program
    serves every split of one pool geometry."""
    return copy_paged_block(pools, src, dst)


@partial(
    jax.jit,
    static_argnames=(
        "chunk", "block_size", "t_max", "n_heads", "eos_id", "greedy",
        "top_k", "nucleus", "moe_top_k", "moe_dispatch",
    ),
    donate_argnums=(1,),
)
def _paged_decode_chunk(
    params, pools, tables, tok, pos, start, done, remaining,
    temperature, top_p, rng, *, chunk, block_size, t_max, n_heads,
    eos_id, greedy, top_k, nucleus, moe_top_k, moe_dispatch,
):
    """Up to ``chunk`` paged decode steps for the whole batch in ONE
    compiled program (the paged twin of :func:`_decode_chunk`).

    Per-row positions are native to the paged step (the block table is
    the indirection — no vmap-into-scatter), so no prompt length,
    admission pattern, block assignment or pool occupancy ever
    recompiles this.  Done/idle rows write to the reserved null block
    and their positions FREEZE (a clamped position could walk into a
    table entry the allocator already handed to another row — the
    dense chunk's clamp-and-ignore trick is not safe against a shared
    pool)."""
    b = tok.shape[0]
    # clamp against the FULL positional capacity, never the (possibly
    # narrower) gathered window: the final loop iteration pushes a live
    # row's pos one past this chunk's allocation, and freezing it at
    # the window edge would overwrite the edge slot next step.  The
    # transiently out-of-window pos is harmless — the host re-windows
    # and re-allocates before the next chunk reads it.
    t_cap = t_max - 1
    fill = jnp.int32(eos_id)
    out = jnp.full((b, chunk), fill, jnp.int32)

    def cond(carry):
        i, _, _, _, done, _, _ = carry
        return (i < chunk) & ~jnp.all(done)

    def body(carry):
        i, pools, tok, pos, done, remaining, out = carry
        pools, logits = paged_decode_step(
            params, pools, tables, tok, pos, n_heads=n_heads,
            block_size=block_size, start=start, write_mask=~done,
            moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
        )
        nxt = _sample_tok(
            logits, jax.random.fold_in(rng, i), temperature, top_p,
            greedy=greedy, top_k=top_k, nucleus=nucleus,
        )
        nxt = jnp.where(done, fill, nxt)
        remaining = jnp.where(done, remaining, remaining - 1)
        done = done | (nxt == eos_id) | (remaining <= 0)
        out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, i))
        pos = jnp.where(done, pos, jnp.minimum(pos + 1, t_cap))
        return (i + 1, pools, nxt, pos, done, remaining, out)

    i, pools, tok, pos, done, remaining, out = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), pools, tok, pos, done, remaining, out),
    )
    return pools, tok, pos, done, remaining, out, i


@partial(
    jax.jit,
    static_argnames=(
        "width", "block_size", "n_heads", "greedy", "top_k", "nucleus",
        "moe_top_k", "moe_dispatch",
    ),
    donate_argnums=(1,),
)
def _paged_verify_prog(
    params, pools, tables, tokens, pos, start, done, n_write,
    draft_len, temperature, top_p, rng, *, width, block_size, n_heads,
    greedy, top_k, nucleus, moe_top_k, moe_dispatch,
):
    """Speculative VERIFY: score ``width`` input tokens per row — the
    row's current last token plus its drafted continuation — in ONE
    forward pass through the paged attention path
    (:func:`paged_verify_chunk`), then keep each row's longest agreeing
    prefix.

    Returns ``(pools, out [B, width], n_accept [B])``: the host emits
    ``out[b, :n_accept[b] + 1]`` — the accepted drafts plus one BONUS
    token (the verifier's own prediction at the first disagreement, or
    past the last accepted draft) — and advances the row's state by
    that many positions.  Greedy: acceptance is exact argmax agreement
    position by position, so the emitted chain is token-identical to
    non-speculative decode (``out`` IS the greedy prediction at every
    position, conditioned on the drafts before it — valid exactly up to
    and including the bonus slot, which is all the host reads).
    Sampled: standard speculative rejection against the drafter's
    point-mass proposal — draft ``d`` at a position is accepted with
    probability ``p(d)`` under the FILTERED target distribution
    (:func:`~znicz_tpu.workflow.generate._filter_logits` — the same
    truncation :func:`_sample` draws through), a rejection resamples
    from the residual (``p`` with ``d`` masked out), and a position
    with no draft samples ``p`` directly — the emitted marginal is the
    target distribution exactly (Leviathan et al. 2023).

    ``width`` is the bucketed verify shape; ``draft_len``/``n_write``
    are TRACED [B] operands, so rows with shorter drafts, smaller
    budgets, or no draft at all (emit 1 token — a plain decode step's
    worth) ride the same compiled program: zero new programs per
    accepted length."""
    b = tokens.shape[0]
    idx = jnp.arange(width)[None, :]
    wmask = (~done)[:, None] & (idx < n_write[:, None])
    pools, logits = paged_verify_chunk(
        params, pools, tables, tokens, pos, n_heads=n_heads,
        block_size=block_size, start=start, write_mask=wmask,
        moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
    )
    # position i predicts the token AFTER input token i; the draft for
    # it is tokens[:, i+1], which exists iff i < draft_len
    has_draft = idx < draft_len[:, None]
    d_next = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((b, 1), jnp.int32)], axis=1
    )
    if greedy:
        out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        acc = (out == d_next) & has_draft
    else:
        flt = _filter_logits(logits, temperature, top_k, nucleus, top_p)
        probs = jax.nn.softmax(flt, axis=-1)
        p_draft = jnp.take_along_axis(probs, d_next[..., None], axis=-1)[
            ..., 0
        ]
        u = jax.random.uniform(jax.random.fold_in(rng, 0), p_draft.shape)
        acc = (u <= p_draft) & has_draft
        # correction at a drafted position resamples the RESIDUAL (the
        # rejected draft masked out); an undrafted position samples the
        # filtered distribution directly (the plain-decode draw)
        vocab = flt.shape[-1]
        is_d = (
            jnp.arange(vocab)[None, None, :] == d_next[..., None]
        ) & has_draft[..., None]
        corr = jax.random.categorical(
            jax.random.fold_in(rng, 1),
            jnp.where(is_d, -jnp.inf, flt),
            axis=-1,
        ).astype(jnp.int32)
        out = jnp.where(acc, d_next, corr)
    n_accept = jnp.sum(
        jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1
    )
    return pools, out, n_accept


class DecodeEngine:
    """Continuous micro-batching front-end over the KV-cache decoder.

    Usage::

        eng = DecodeEngine(params, n_heads=8, eos_id=0, batch_size=8)
        ids = [eng.submit(prompt, max_new_tokens=64) for prompt in reqs]
        completions = eng.run()          # drain the queue
        eng.stats()                      # latency / tokens/s / compiles

    Greedy by default; ``temperature``/``top_k``/``top_p`` select the
    same sampling structures as :func:`generate` (one compiled program
    set per structure).  ``admit_every`` is the admission granularity:
    the batch decodes in chunks of that many steps between retirement
    checks — small values admit sooner, large values sync less."""

    kv_backend = "dense"

    def __init__(
        self,
        params,
        *,
        n_heads: int,
        eos_id: int,
        batch_size: int = 8,
        max_seq: Optional[int] = None,
        prompt_buckets: Sequence[int] = DEFAULT_PROMPT_BUCKETS,
        admit_every: int = 8,
        pad_id: Optional[int] = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        rng: Optional[jax.Array] = None,
        moe_top_k: int = 1,
        moe_dispatch: str = "dense",
        prefix_cache: Optional[bool] = None,
        spec_k: int = 0,
        drafter=None,
        spec_buckets: Optional[Sequence[int]] = None,
    ):
        if batch_size < 1 or admit_every < 1:
            raise ValueError(
                f"want batch_size >= 1 and admit_every >= 1; got "
                f"{batch_size}, {admit_every}"
            )
        if prefix_cache:
            raise ValueError(
                "prefix cache requires the paged backend "
                "(PagedDecodeEngine): the dense [B, T_max] KV layout has "
                "no shareable blocks to map across requests"
            )
        if spec_k or drafter is not None or spec_buckets is not None:
            # typed CONFIG error (docs/SERVING.md failure taxonomy):
            # rollback of rejected drafts is a block-table truncate,
            # which the dense layout has no tables to perform
            raise SpeculationUnsupportedError(
                "speculative decoding requires the paged backend "
                "(PagedDecodeEngine): rejected draft tokens roll back "
                "by truncating the row's block table — the dense "
                "[B, T_max] KV layout has no block tables to truncate"
            )
        if not hasattr(self, "spec_k"):
            self.spec_k = 0  # the stats() spec sub-dict reads this
            # (the paged subclass sets its own before delegating here)
        max_pos = params[0]["pos"].shape[0]
        self.t_max = int(max_seq or max_pos)
        if self.t_max > max_pos:
            raise ValueError(
                f"max_seq {self.t_max} exceeds the positional table "
                f"({max_pos})"
            )
        top_k, rng = _check_sampling_args(
            params, temperature, top_k, top_p, rng, eos_id
        )
        self.params = params
        self._params_fp = _params_fingerprint(params)
        self.n_heads = n_heads
        self.eos_id = int(eos_id)
        self.pad_id = int(pad_id if pad_id is not None else eos_id)
        self.batch_size = int(batch_size)
        self.prompt_buckets = tuple(prompt_buckets)
        self.admit_every = int(admit_every)
        self.moe_top_k = moe_top_k
        self.moe_dispatch = moe_dispatch
        self._temperature = jnp.float32(temperature)
        self._top_p = jnp.float32(top_p)
        self._rng = rng
        # static sampling structure: one compiled program set per value
        self._structure = (temperature == 0.0, top_k, top_p < 1.0)
        b = self.batch_size
        self._tok = np.zeros((b,), np.int32)
        self._pos = np.zeros((b,), np.int32)
        self._start = np.zeros((b,), np.int32)
        self._done = np.ones((b,), bool)  # empty slots idle as done
        self._remaining = np.zeros((b,), np.int32)
        self._slots: List[Optional[dict]] = [None] * b
        self._queue: Deque[Request] = deque()
        self._order: List[Completion] = []
        self.completions: Dict[int, Completion] = {}
        # process-wide registry series (shared across engines: get-or-
        # create); per-instance windows ride LatencyStats / PhaseTimer
        self._m_submitted = observability.counter(
            "znicz_serve_requests_submitted_total",
            "requests accepted into the engine queue",
        )
        self._m_admitted = observability.counter(
            "znicz_serve_requests_admitted_total",
            "requests prefilled into a batch slot",
        )
        self._m_retired = observability.counter(
            "znicz_serve_requests_retired_total",
            "completed requests by finish reason",
            ("reason",),
        )
        self._m_tokens = observability.counter(
            "znicz_serve_tokens_generated_total",
            "generated tokens across all retired requests",
        )
        self._m_compiles = observability.counter(
            "znicz_serve_compiles_total",
            "distinct compiled engine programs by kind and bucket",
            ("kind", "bucket"),
        )
        self._m_program_hits = observability.counter(
            "znicz_serve_program_hits_total",
            "program invocations served from an already-compiled entry",
        )
        self._m_queue_depth = observability.gauge(
            "znicz_serve_queue_depth", "requests waiting for a slot"
        )
        self._m_active = observability.gauge(
            "znicz_serve_active_slots", "batch slots decoding right now"
        )
        self._m_latency = observability.histogram(
            "znicz_serve_request_latency_seconds",
            "submit -> retirement latency per request (queue wait included)",
        )
        self._m_ttft = observability.histogram(
            "znicz_serve_ttft_seconds",
            "submit -> first sampled token per request",
        )
        # per-tick occupancy: what fraction of each engine tick's wall
        # went to admission/prefill vs the decode chunk vs a spec-verify
        # chunk — the measured input the spec-aware-SLO-tuning and
        # scheduling rungs consume (ROADMAP).  Fractions, not seconds:
        # a tick is the scheduling quantum, so its internal split is
        # the signal (wall itself rides znicz_serve_phase_seconds)
        self._m_tick_occ = observability.histogram(
            "znicz_serve_tick_occupancy",
            "per-tick fraction of wall spent by phase "
            "(prefill / decode / spec_verify)",
            ("phase",),
            buckets=observability.DEFAULT_FRACTION_BUCKETS,
        )
        self._occ_seconds = {
            "prefill": 0.0, "decode": 0.0, "spec_verify": 0.0,
        }
        self._occ_wall = 0.0
        self._occ_ticks = 0
        # which kind of chunk the last _run_chunk ran ("decode" or
        # "spec_verify") — written by the paged subclass's spec path
        self._last_chunk_kind = "decode"
        self.latency = profiling.LatencyStats(
            observe=self._m_latency.observe
        )
        self.timer = observability.PhaseTimer(
            "znicz_serve_phase_seconds",
            help="engine admit/decode host phase seconds",
            span_prefix="serve/",
        )
        # fleet tracing: the serving instance this engine's spans
        # belong to (set by the front door; rides every span/instant
        # as an ``instance`` arg so the trace collector's merged view
        # can split an in-process fleet into per-instance tracks)
        self.trace_instance: Optional[str] = None
        self._programs: Dict[tuple, int] = {}
        self._program_hits = 0
        self._next_id = 0
        self._n_admits = 0
        self._chunk_idx = 0
        self._total_new = 0
        self._peak_active = 0
        self._init_kv_state()

    def _init_kv_state(self) -> None:
        """Allocate the dense ``[B, T_max]`` KV buffers (the paged
        subclass overrides this with a block pool + tables)."""
        self._caches = init_kv_cache(
            self.params, self.batch_size, self.t_max, n_heads=self.n_heads
        )

    # -- request intake ---------------------------------------------------

    def _validate_request(self, p: np.ndarray, max_new_tokens: int) -> int:
        """Check the request against THIS backend's real KV capacity;
        returns the admission width (prompt bucket).  Backend-specific so
        the error names what actually ran out — the dense buffer's
        ``t_max`` window here, the block pool in the paged subclass."""
        bucket = bucket_for(p.size, self.prompt_buckets)
        if bucket + max_new_tokens > self.t_max:
            raise RequestTooLargeError(
                f"prompt bucket {bucket} (len {p.size}) + max_new_tokens "
                f"{max_new_tokens} exceeds the dense KV buffer "
                f"(t_max={self.t_max})"
            )
        return bucket

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        trace_id: Optional[str] = None,
    ) -> int:
        """Queue one prompt (1-D token ids); returns the request id.
        Validated against the active backend's real KV capacity, so
        admission can never fail later.  ``trace_id`` (the front door's
        client-visible id) rides into the request's lifecycle spans and
        its completion."""
        p = np.asarray(prompt, np.int32).reshape(-1)
        if p.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"want max_new_tokens >= 1; got {max_new_tokens}")
        bucket = self._validate_request(p, max_new_tokens)
        rid = self._next_id
        self._next_id += 1
        self._queue.append(
            Request(rid, p, int(max_new_tokens), bucket,
                    profiling.Stopwatch(), trace_id=trace_id)
        )
        self._m_submitted.inc()
        self._m_queue_depth.set(len(self._queue))
        observability.instant(
            "serve/queued", id=rid, **self._trace_args(trace_id)
        )
        return rid

    def _trace_args(self, trace_id: Optional[str]) -> Dict:
        """Span/instant args for a trace id — empty when none, so
        engine-direct callers add no noise to the timeline.  When the
        front door names this engine's instance
        (:attr:`trace_instance`), every span carries it too — the
        fleet trace collector groups the merged timeline by that tag
        (pid=instance in Perfetto)."""
        args: Dict = {}
        if trace_id:
            args["trace"] = trace_id
        if self.trace_instance:
            args["instance"] = self.trace_instance
        return args

    def _decode_trace_args(self, residents) -> Dict:
        """Decode chunks are batched: the span carries EVERY resident's
        trace id (comma-joined) so ONE Perfetto trace-id filter also
        surfaces the decode chunks a request was resident in."""
        args: Dict = {}
        traces = ",".join(
            r.trace_id for r in residents if r.trace_id
        )
        if traces:
            args["traces"] = traces
        if self.trace_instance:
            args["instance"] = self.trace_instance
        return args

    @property
    def pending(self) -> int:
        return len(self._queue)

    @property
    def active(self) -> int:
        return sum(s is not None for s in self._slots)

    # -- the serving loop -------------------------------------------------

    def run(self) -> List[Completion]:
        """Drain the queue: admit into free slots, decode in chunks,
        retire finished rows, re-admit — until every submitted request
        has completed.  Returns this call's completions in retirement
        order (also kept in :attr:`completions` by id)."""
        n0 = len(self._order)
        while self.tick():
            pass
        return self._order[n0:]

    def tick(self) -> bool:
        """ONE engine tick — admit + prefill, then a decode (or
        spec-verify) chunk — with the per-phase wall split observed
        into ``znicz_serve_tick_occupancy{phase}``.  Returns False when
        there is no work (nothing ran).  Both :meth:`run` and the front
        door's engine thread drive the engine through this, so the
        occupancy series is the one truth for tick composition."""
        if not self._has_work():
            return False
        t0 = time.perf_counter()
        self._admit_pending()
        self._prefill_tick()
        t1 = time.perf_counter()
        chunk_kind = None
        if self.active:
            self._last_chunk_kind = "decode"
            self._run_chunk()
            chunk_kind = self._last_chunk_kind
        t2 = time.perf_counter()
        self._observe_tick(t1 - t0, t2 - t1, chunk_kind)
        return True

    def _observe_tick(
        self,
        prefill_s: float,
        chunk_s: float,
        chunk_kind: Optional[str],
    ) -> None:
        wall = prefill_s + chunk_s
        if wall <= 0:
            return
        frac = {"prefill": prefill_s / wall}
        if chunk_kind is not None:
            frac[chunk_kind] = chunk_s / wall
        for phase, f in frac.items():
            self._m_tick_occ.labels(phase=phase).observe(f)
        self._occ_seconds["prefill"] += prefill_s
        if chunk_kind is not None:
            self._occ_seconds[chunk_kind] += chunk_s
        self._occ_wall += wall
        self._occ_ticks += 1

    def tick_occupancy(self) -> Dict:
        """Lifetime tick-composition report (the ``stats()`` entry):
        tick count, total tick wall, and each phase's fraction of it."""
        wall = self._occ_wall
        return {
            "ticks": self._occ_ticks,
            "wall_s": round(wall, 6),
            "frac": {
                k: round(v / wall, 4) if wall > 0 else 0.0
                for k, v in self._occ_seconds.items()
            },
        }

    def _has_work(self) -> bool:
        return bool(self._queue) or self.active > 0

    def _prefill_tick(self) -> None:
        """Dense admission prefills whole prompts inside
        :meth:`_admit_pending`; the paged subclass interleaves one
        prompt CHUNK per prefilling slot here, between decode chunks."""

    def _program(self, key: tuple) -> bool:
        """Ledger one executable per key: the compile-count hook's
        ground truth (tests cross-check it against the jit cache).
        Registry mirror: ``znicz_serve_compiles_total{kind,bucket}``
        counts TRUE first compiles per (params geometry, key) across the
        whole process — a second engine with the same geometry rides the
        shared jit caches and adds nothing.  ``key[1]`` is the prompt
        bucket for admits, the chunk size for the decode program.
        Returns True exactly when this call IS a true first compile
        (the device-ledger hook in :meth:`_timed_program` keys off
        it, so ``/debug/programs`` stays count-identical to the
        counter)."""
        if key in self._programs:
            self._program_hits += 1
            self._m_program_hits.inc()
            return False
        self._programs[key] = 1
        full_key = (self._params_fp, key)
        if full_key in _COMPILED_KEYS:
            return False
        _COMPILED_KEYS.add(full_key)
        self._m_compiles.labels(kind=key[0], bucket=key[1]).inc()
        return True

    def _timed_program(self, key: tuple, fn, *args, **kwargs):
        """Ledger + invoke one compiled program.  On its TRUE first
        compile (process-wide, :meth:`_program`'s dedup) the call is
        wall-timed and recorded into the device ledger
        (``/debug/programs``, ``znicz_compile_seconds``,
        ``znicz_program_cost_*``) together with the lowering's cost
        analysis; steady-state invocations pay one dict lookup and
        nothing else.  The recorded wall time is the first dispatch —
        trace + compile + the first execution — which on a first
        compile is compile-dominated."""
        if not self._program(key):
            return fn(*args, **kwargs)
        cost = device_telemetry.lowered_cost(fn, args, kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        device_telemetry.record_program(
            key,
            time.perf_counter() - t0,
            cost=cost,
            dedup=(self._params_fp, key),
        )
        return out

    def _admit_pending(self) -> None:
        for slot in range(self.batch_size):
            # keep pulling from the queue until the slot holds an ACTIVE
            # row: a request that retires at admission itself (first
            # token is EOS, or budget 1) must not idle the slot for a
            # whole decode chunk
            while self._queue and self._slots[slot] is None:
                self._admit_into(slot, self._queue.popleft())
        self._m_queue_depth.set(len(self._queue))
        self._m_active.set(self.active)

    def _admit_into(self, slot: int, req: Request) -> None:
        req.timings.queue_s += req.watch.elapsed() - req.last_queued_at
        t0 = time.perf_counter()
        with self.timer.phase(
            "admit", request=req.id, bucket=req.bucket,
            **self._trace_args(req.trace_id),
        ):
            tokens, start = pack_prompts(
                [req.prompt], req.bucket, self.pad_id
            )
            key = jax.random.fold_in(self._rng, self._n_admits)
            self._n_admits += 1
            greedy, top_k, nucleus = self._structure
            self._caches, first = self._timed_program(
                ("admit", req.bucket, self._structure),
                _admit_row,
                self.params, self._caches, tokens, start,
                jnp.int32(slot), self._temperature, self._top_p, key,
                n_heads=self.n_heads, greedy=greedy, top_k=top_k,
                nucleus=nucleus, moe_top_k=self.moe_top_k,
                moe_dispatch=self.moe_dispatch,
            )
            first = int(first)
        req.timings.prefill_s += time.perf_counter() - t0
        self._m_admitted.inc()
        req.ttft_s = req.watch.elapsed()
        self._m_ttft.observe(req.ttft_s)
        if first == self.eos_id:
            self._retire(req, [first], "eos")
        elif req.max_new_tokens == 1:
            self._retire(req, [first], "budget")
        else:
            self._slots[slot] = {"req": req, "emitted": [first]}
            self._tok[slot] = first
            self._pos[slot] = req.bucket
            self._start[slot] = req.bucket - req.prompt.size
            self._done[slot] = False
            self._remaining[slot] = req.max_new_tokens - 1

    def _run_chunk(self) -> None:
        faults.fire("engine.decode_step")
        self._peak_active = max(self._peak_active, self.active)
        residents = [
            st["req"] for st in self._slots if st is not None
        ]
        t0 = time.perf_counter()
        with self.timer.phase(
            "decode", active=self.active,
            **self._decode_trace_args(residents),
        ):
            rng = jax.random.fold_in(self._rng, 1 << 20 | self._chunk_idx)
            self._chunk_idx += 1
            greedy, top_k, nucleus = self._structure
            (caches, tok, pos, done, remaining, out, steps) = (
                self._timed_program(
                    ("chunk", self.admit_every, self.batch_size,
                     self._structure),
                    _decode_chunk,
                    self.params, self._caches, jnp.asarray(self._tok),
                    jnp.asarray(self._pos), jnp.asarray(self._start),
                    jnp.asarray(self._done),
                    jnp.asarray(self._remaining),
                    self._temperature, self._top_p, rng,
                    chunk=self.admit_every, n_heads=self.n_heads,
                    eos_id=self.eos_id, greedy=greedy, top_k=top_k,
                    nucleus=nucleus, moe_top_k=self.moe_top_k,
                    moe_dispatch=self.moe_dispatch,
                )
            )
            self._caches = caches
            # ONE host sync per chunk — the admission granularity; the
            # [B]-sized state and [B, chunk] emissions are tiny next to
            # the device-resident KV buffers
            out = np.asarray(out)
            steps = int(steps)
            # np.array (not asarray): host state stays mutable — asarray
            # of a device array is a read-only view
            self._tok = np.array(tok)
            self._pos = np.array(pos)
            self._done = np.array(done)
            self._remaining = np.array(remaining)
        dt = time.perf_counter() - t0
        for r in residents:
            r.timings.decode_s += dt
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            req, emitted = st["req"], st["emitted"]
            reason = None
            for t in out[slot, :steps]:
                emitted.append(int(t))
                if int(t) == self.eos_id:
                    reason = "eos"
                    break
                if len(emitted) >= req.max_new_tokens:
                    reason = "budget"
                    break
            if reason is not None:
                self._retire(req, emitted, reason)
                self._slots[slot] = None
                self._done[slot] = True
                self._remaining[slot] = 0
        self._m_active.set(self.active)

    def _retire(self, req: Request, emitted: List[int], reason: str):
        dt = req.watch.elapsed()
        comp = Completion(
            id=req.id,
            tokens=np.concatenate(
                [req.prompt, np.asarray(emitted, np.int32)]
            ),
            n_new=len(emitted),
            finish_reason=reason,
            latency_s=dt,
            tokens_per_sec=len(emitted) / max(dt, 1e-9),
            bucket=req.bucket,
            ttft_s=req.ttft_s,
            trace_id=req.trace_id,
            timings=req.timings.as_dict(),
        )
        self._order.append(comp)
        self.completions[req.id] = comp
        # feeds the shared registry histogram via the observe hook
        self.latency.record(dt)
        self._total_new += len(emitted)
        self._m_retired.labels(reason=reason).inc()
        self._m_tokens.inc(len(emitted))
        observability.instant(
            "serve/retired", id=req.id, reason=reason,
            **self._trace_args(req.trace_id),
        )

    # -- out-of-band retirement (cancellation / deadlines) ----------------

    def abort(self, request_id: int, reason: str) -> Optional[Completion]:
        """Retire a request OUT OF BAND with a typed completion —
        cancellation or deadline expiry, driven by the front door
        between ticks.  Works wherever the request currently lives:
        still queued (removed, zero tokens) or occupying a slot
        (tokens emitted so far are kept; the slot — and on the paged
        backend its blocks — is reclaimed immediately).  Returns the
        typed :class:`Completion`, or None when the id is unknown or
        already completed (the normal completion wins the race).

        NOT thread-safe: call only from the thread that drives the
        engine (the front door's engine thread)."""
        for i, req in enumerate(self._queue):
            if req.id == request_id:
                del self._queue[i]
                self._m_queue_depth.set(len(self._queue))
                # the whole wait so far was queueing: close it out so
                # the timings of a queued abort say where the time went
                req.timings.queue_s += (
                    req.watch.elapsed() - req.last_queued_at
                )
                self._retire(req, [], reason)
                return self.completions[request_id]
        for slot, st in enumerate(self._slots):
            if st is not None and st["req"].id == request_id:
                self._abort_slot(slot, reason)
                return self.completions[request_id]
        return None

    def reap(self, request_id: int) -> None:
        """Forget a completed request's record.  The front door copies
        each completion into its own handle as it collects it — keeping
        the engine-side ``completions``/retirement-order ledgers for
        every request ever served would leak on a long-lived service.
        Batch-style callers that use :meth:`run` never need this."""
        if self.completions.pop(request_id, None) is not None:
            self._order = [c for c in self._order if c.id != request_id]

    def _abort_slot(self, slot: int, reason: str) -> None:
        """Dense out-of-band slot retirement: the slot just empties —
        its stale K/V is rebuilt from a zeroed row at re-admission."""
        st = self._slots[slot]
        self._retire(st["req"], list(st.get("emitted") or []), reason)
        self._slots[slot] = None
        self._done[slot] = True
        self._remaining[slot] = 0
        self._m_active.set(self.active)

    # -- introspection ----------------------------------------------------

    def prefix_probe(self, prompt) -> Dict:
        """Public prefix-cache probe: the prompt's chained block keys
        (:func:`prefix_block_keys`) and how many of its lead blocks are
        already cached HERE.  The dense backend has no shareable blocks,
        so its answer is the empty probe — the router (and tests) read
        this hook instead of engine privates; the paged subclass
        overrides it with the real cache walk."""
        np.asarray(prompt, np.int32).reshape(-1)  # same coercion contract
        return {
            "prefix_cache": False,
            "block_size": None,
            "block_keys": [],
            "cached_blocks": 0,
            "cached_tokens": 0,
        }

    def compile_stats(self) -> Dict:
        """Compile-count hook: ``programs`` maps each
        ``("admit", bucket, structure)`` / ``("chunk", chunk, B,
        structure)`` key to 1 — one executable per key over the engine's
        lifetime; ``program_hits`` counts invocations that reused one.
        ``*_jit_entries`` are the process-wide jax caches backing them
        (shared across engines: a second engine with the same geometry
        compiles nothing new)."""
        return {
            "programs": dict(self._programs),
            "n_programs": len(self._programs),
            "program_hits": self._program_hits,
            "admit_jit_entries": _admit_row._cache_size(),
            "chunk_jit_entries": _decode_chunk._cache_size(),
        }

    def spec_stats(self) -> Dict:
        """The ``spec`` sub-dict of :meth:`stats`: the dense backend
        cannot speculate (construction rejects it), so its answer is
        the disabled report — callers read ONE shape whichever backend
        serves (the paged subclass overrides with the live tallies)."""
        return {"enabled": False}

    def stats(self) -> Dict:
        """Serving report: completions, generated tokens, the per-request
        latency aggregate, per-phase host timings, compile counts and
        the speculative-decoding sub-dict (:meth:`spec_stats`).
        ``peak_active`` is the max rows decoding in one chunk — the
        engine's observed concurrency (the paged backend's headline)."""
        return {
            "kv_backend": self.kv_backend,
            "completed": len(self.completions),
            "generated_tokens": self._total_new,
            "peak_active": self._peak_active,
            "latency": self.latency.summary(),
            "phases": self.timer.summary(),
            "tick_occupancy": self.tick_occupancy(),
            "spec": self.spec_stats(),
            **self.compile_stats(),
        }


class PagedDecodeEngine(DecodeEngine):
    """Paged-KV continuous batching: refcounted copy-on-write block
    pool, cross-request prefix cache, chunked prefill, preemption under
    pressure (docs/SERVING.md "Paged KV serving").

    Same queue surface as :class:`DecodeEngine` (``submit``/``run``/
    ``stats``), different memory model: K/V live in a shared
    ``[n_blocks, block_size, H, hd]`` pool per layer; each slot owns an
    ordered block table and every pool block carries a REFCOUNT — the
    same physical block can appear in many tables at once.  Four
    properties follow:

    * **memory-proportional concurrency** — a slot consumes blocks for
      the tokens it has actually decoded, not a ``T_max`` reservation;
      ``n_blocks`` (not ``batch_size * T_max``) is the real capacity,
      so short requests pack many-deep into the same memory.
    * **prefix reuse (RadixAttention/vLLM lineage)** — retiring (and
      preempted) requests publish their COMPLETED full blocks into a
      prefix cache keyed by CHAINED content hash (block j's key commits
      to all tokens before it — an implicit radix structure); admission
      maps the longest cached block-chain prefix of the prompt into the
      new table with refcount bumps and chunk-prefills only the
      uncached tail.  A fully-cached system prompt costs zero prefill
      FLOPs (one chunk reruns for the first-token logits) and TTFT
      collapses to the tail.  Shared blocks are READ-ONLY: a write into
      a block other tables or the cache reference COW-splits it first.
      Prompts anchor at position 0 and right-pad the final chunk so a
      shared prefix fills identical block contents whatever the full
      prompt's length.
    * **chunked prefill** — prompts are processed in block-sized chunks
      under a per-tick TOKEN budget (``prefill_budget``,
      Sarathi-style), interleaved with decode chunks: admitting a long
      prompt steals a bounded slice of tower work between decode chunks
      instead of stalling rows mid-decode.
    * **eviction before preemption** — when the free list is dry,
      allocation first EVICTS the least-recently-used cache-only block
      (refcount 0, cache-referenced); only when the cache too is empty
      is the YOUNGEST occupant preempted: publishes its full blocks to
      the cache, releases its references, requeues at the queue head
      for recompute on readmission (often straight out of its own
      just-cached blocks).  Refcounts keep survivors' shared blocks
      alive through any preemption.  If the starved slot is itself the
      youngest it requeues itself and waits for older rows to retire;
      submit-time validation guarantees any single request fits an
      empty pool, so the wait always terminates.

    ONE prefill program plus a short x2 ladder of decode-chunk
    variants cover any stream (vs the dense engine's
    one-admit-per-bucket): the ``[1, block_size]`` prefill chunk
    serves every prompt length, and the decode chunk is keyed only by
    the active block-WINDOW rung (the gather spans the blocks active
    rows actually hold, rounded up a power of two — so short requests
    don't pay ``T_max``-wide attention and the variant count stays
    logarithmic); block tables, chunk offsets, pool occupancy,
    admission patterns AND prefix-cache hits are all traced operands —
    prefix reuse adds ZERO compiled programs, it only skips iterations
    of the existing chunk program.

    ``block_size`` trades utilization against program width;
    ``n_blocks`` defaults to the dense-equivalent footprint
    (``batch_size * ceil(T_max/block_size) + 1``) — size it DOWN to
    serve the same stream in less memory, or raise ``batch_size``
    against the same pool to convert reclaimed padding into
    concurrency.  ``prefix_cache=False`` disables sharing (blocks then
    free directly at release, LIFO)."""

    kv_backend = "paged"

    def __init__(
        self,
        params,
        *,
        n_heads: int,
        eos_id: int,
        batch_size: int = 8,
        max_seq: Optional[int] = None,
        block_size: int = 16,
        n_blocks: Optional[int] = None,
        prefill_budget: Optional[int] = None,
        prefix_cache: Optional[bool] = None,
        admit_every: int = 8,
        pad_id: Optional[int] = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        rng: Optional[jax.Array] = None,
        moe_top_k: int = 1,
        moe_dispatch: str = "dense",
        spec_k: int = 0,
        drafter=None,
        spec_buckets: Sequence[int] = DEFAULT_SPEC_BUCKETS,
    ):
        if block_size < 1:
            raise ValueError(f"want block_size >= 1; got {block_size}")
        self.block_size = int(block_size)
        self._n_blocks_arg = n_blocks
        # ON by default: sharing is free when nothing matches (a few
        # sha256 per admission) and the headline win when it does
        self.prefix_cache = True if prefix_cache is None else bool(
            prefix_cache
        )
        # speculative decoding (docs/SERVING.md "Speculative decoding"):
        # spec_k == 0 is OFF (the plain decode chunk runs); > 0 drafts
        # up to spec_k tokens per decoding row each tick and verifies
        # them in one bucketed forward pass.  The drafter is duck-typed
        # (``propose(context, k)``) — prompt-lookup by default, a
        # draft-model drafter plugs into the same hook.
        if spec_k < 0:
            raise ValueError(f"want spec_k >= 0; got {spec_k}")
        self.spec_k = int(spec_k)
        self.spec_buckets = tuple(int(w) for w in spec_buckets)
        if (
            not self.spec_buckets
            or min(self.spec_buckets) < 2
            or list(self.spec_buckets)
            != sorted(set(self.spec_buckets))
        ):
            raise ValueError(
                "spec_buckets must be strictly increasing verify "
                f"widths >= 2 (k+1 rungs); got {spec_buckets}"
            )
        if drafter is not None and not self.spec_k:
            # silently serving with speculation OFF would be a config
            # trap (the dense backend raises for the same noise)
            raise ValueError(
                "a drafter was given but spec_k == 0 keeps speculation "
                "off; pass spec_k >= 1 to enable it"
            )
        self.drafter = (
            drafter if drafter is not None else PromptLookupDrafter()
        ) if self.spec_k else None
        # per-tick prefill token budget: how much admission work may
        # ride between two decode chunks.  The default matches one
        # decode chunk's per-row depth (admit_every steps) in tokens —
        # admission and decode then make comparable progress per tick
        self.prefill_budget = int(
            prefill_budget if prefill_budget is not None
            else max(admit_every, 1) * self.block_size
        )
        if self.prefill_budget < 1:
            raise ValueError(
                f"want prefill_budget >= 1; got {self.prefill_budget}"
            )
        super().__init__(
            params, n_heads=n_heads, eos_id=eos_id,
            batch_size=batch_size, max_seq=max_seq,
            admit_every=admit_every, pad_id=pad_id,
            temperature=temperature, top_k=top_k, top_p=top_p, rng=rng,
            moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
        )

    def _init_kv_state(self) -> None:
        m = -(-self.t_max // self.block_size)  # table width: ceil
        if self._n_blocks_arg is None:
            # dense-equivalent default: every slot could hold a full
            # T_max window (plus the reserved null block) — same memory
            # as the dense engine, minus nothing; shrink it to save
            self.n_blocks = self.batch_size * m + 1
        else:
            self.n_blocks = int(self._n_blocks_arg)
        self.blocks_per_row = m
        self._pools = init_paged_kv(
            self.params, self.n_blocks, self.block_size,
            n_heads=self.n_heads,
        )
        # LIFO free list: a just-freed (still cache/HBM-warm) block is
        # the next one handed out; block 0 stays reserved as null
        self._free: List[int] = list(range(1, self.n_blocks))
        # per-block refcount = how many tables reference it; the cache
        # reference is tracked separately by _block_hash membership
        self._ref = np.zeros(self.n_blocks, np.int64)
        # prefix cache: chained content hash -> block, its inverse, and
        # an LRU over CACHE-ONLY blocks (refcount 0: evictable)
        self._cache: Dict[bytes, int] = {}
        self._block_hash: Dict[int, bytes] = {}
        self._lru: OrderedDict = OrderedDict()
        self._row_blocks: List[List[int]] = [
            [] for _ in range(self.batch_size)
        ]
        self._tables = np.full(
            (self.batch_size, m), NULL_BLOCK, np.int32
        )
        self._n_prefix_hits = 0
        self._n_prefix_misses = 0
        self._n_cached_tokens = 0
        self._n_evictions = 0
        self._n_cow = 0
        # one admission EVENT per request, ever: a preempted request's
        # readmission must not re-fire the serve/admit span, the
        # admitted counter, or the TTFT histogram (its first token was
        # already produced once — re-observing would double-count)
        self._admitted_ids: set = set()
        self._n_preempted = 0
        # per-block K/V footprint across the whole tower — the byte
        # twin of the block gauges, so pool pressure is readable in
        # the same unit device memory is
        self.block_bytes = sum(
            2 * int(np.prod(p["k"].shape[1:]))
            * np.dtype(p["k"].dtype).itemsize
            for p in self._pools
        )
        self._m_pool = observability.gauge(
            "znicz_serve_kv_pool_blocks",
            "paged KV pool blocks by state (the null block is excluded)",
            ("state",),
        )
        self._m_pool_bytes = observability.gauge(
            "znicz_serve_kv_pool_bytes",
            "paged KV pool bytes by state (blocks x per-block K/V "
            "bytes across the tower; the null block is excluded)",
            ("state",),
        )
        self._m_preempted = observability.counter(
            "znicz_serve_preemptions_total",
            "requests preempted under pool pressure (freed + requeued)",
        )
        self._m_prefill_chunks = observability.counter(
            "znicz_serve_prefill_chunks_total",
            "prompt chunks run by the paged prefill program",
        )
        self._m_prefix_hits = observability.counter(
            "znicz_serve_prefix_hits_total",
            "prompt blocks mapped from the prefix cache at admission",
        )
        self._m_prefix_misses = observability.counter(
            "znicz_serve_prefix_misses_total",
            "full prompt blocks that missed the prefix cache at admission",
        )
        self._m_prefix_tokens = observability.counter(
            "znicz_serve_prefix_cached_tokens_total",
            "prompt tokens whose prefill was skipped via the prefix cache",
        )
        self._m_prefix_evictions = observability.counter(
            "znicz_serve_prefix_evictions_total",
            "cached blocks evicted to satisfy allocation pressure",
        )
        # speculative decoding tallies (zero and silent while spec is
        # off; the registry series are process-wide get-or-create)
        self._n_spec_drafted = 0
        self._n_spec_accepted = 0
        self._n_spec_rejected = 0
        self._n_verify_steps = 0
        self._m_spec_drafted = observability.counter(
            "znicz_serve_spec_drafted_total",
            "draft tokens proposed to the speculative verifier",
        )
        self._m_spec_accepted = observability.counter(
            "znicz_serve_spec_accepted_total",
            "draft tokens the speculative verifier accepted",
        )
        self._m_spec_rejected = observability.counter(
            "znicz_serve_spec_rejected_total",
            "draft tokens rejected and rolled back (table truncate)",
        )
        self._m_spec_accept_len = observability.histogram(
            "znicz_serve_spec_accept_length",
            "accepted draft tokens per row per verify step",
            buckets=(0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0),
        )
        self._update_pool_gauges()

    # -- capacity & the block allocator -----------------------------------

    @property
    def usable_blocks(self) -> int:
        """Pool capacity available to requests (null block excluded)."""
        return self.n_blocks - 1

    def _validate_request(self, p: np.ndarray, max_new_tokens: int) -> int:
        padded = -(-p.size // self.block_size) * self.block_size
        total = padded + max_new_tokens
        need = -(-total // self.block_size)
        if total > self.t_max:
            raise RequestTooLargeError(
                f"prompt (len {p.size}, padded {padded}) + max_new_tokens "
                f"{max_new_tokens} exceeds the paged backend's positional "
                f"window (t_max={self.t_max})"
            )
        if need > self.usable_blocks:
            raise RequestTooLargeError(
                f"prompt (len {p.size}, padded {padded}) + max_new_tokens "
                f"{max_new_tokens} needs {need} KV blocks; exceeds the "
                f"paged KV pool ({self.usable_blocks} usable blocks x "
                f"{self.block_size} tokens)"
            )
        return padded  # admission width: the padded prompt length

    def _update_pool_gauges(self) -> None:
        free = len(self._free)
        cached = len(self._lru)
        used = self.usable_blocks - free - cached
        self._m_pool.labels(state="free").set(free)
        self._m_pool.labels(state="cached").set(cached)
        self._m_pool.labels(state="used").set(used)
        bb = self.block_bytes
        self._m_pool_bytes.labels(state="free").set(free * bb)
        self._m_pool_bytes.labels(state="cached").set(cached * bb)
        self._m_pool_bytes.labels(state="used").set(used * bb)

    def _slots_by_age(self) -> List[int]:
        """Occupied slot indices, oldest admission first — allocation
        runs in this order so seniority decides who survives pressure."""
        occ = [
            (self._slots[i]["seq"], i)
            for i in range(self.batch_size)
            if self._slots[i] is not None
        ]
        return [i for _, i in sorted(occ)]

    def _youngest_slot(self) -> int:
        return max(
            (i for i in range(self.batch_size) if self._slots[i] is not None),
            key=lambda i: self._slots[i]["seq"],
        )

    def _incref(self, blk: int) -> None:
        self._ref[blk] += 1

    def _decref(self, blk: int) -> None:
        """Drop one table reference; at zero the block becomes
        EVICTABLE cache (if published) or returns to the free list."""
        self._ref[blk] -= 1
        if self._ref[blk] == 0:
            if blk in self._block_hash:
                # fresh insertion lands at the MRU end (a block enters
                # the LRU only here, and claiming removed it first)
                self._lru[blk] = None
            else:
                self._free.append(blk)

    def _alloc_block(self) -> int:
        """One unreferenced, uncached block: free list first, then
        EVICT the least-recently-used cache-only block — the cache
        always yields before any live request is preempted.  Returns
        -1 when both are dry (the caller preempts)."""
        faults.fire("pool.alloc")  # injected allocator failure (raises)
        if faults.fire("pool.pressure"):
            return -1  # injected exhaustion: free list AND cache "dry"
        if self._free:
            return self._free.pop()
        if self._lru:
            blk, _ = self._lru.popitem(last=False)
            del self._cache[self._block_hash.pop(blk)]
            self._n_evictions += 1
            self._m_prefix_evictions.inc()
            return blk
        return -1

    def _alloc_for(self, slot: int) -> Optional[int]:
        """One referenced block for ``slot``, preempting the youngest
        occupant while the pool (free list AND evictable cache) stays
        dry.  None when the starved slot was itself the youngest and
        got preempted (its request is back in the queue)."""
        while True:
            blk = self._alloc_block()
            if blk >= 0:
                self._incref(blk)
                return blk
            victim = self._youngest_slot()
            self._preempt(victim)
            if victim == slot:
                return None

    def _release_row(self, slot: int) -> None:
        """Drop every table reference of ``slot`` (reverse order keeps
        the free list LIFO — last-allocated, still-warm block first)."""
        row = self._row_blocks[slot]
        for blk in reversed(row):
            self._decref(blk)
        row.clear()
        self._tables[slot, :] = NULL_BLOCK
        self._update_pool_gauges()

    def _preempt(self, slot: int) -> None:
        """Evict ``slot``: publish its completed full blocks into the
        prefix cache (cache-only blocks are the first thing allocation
        consumes, so a transient preemption often readmits straight out
        of its own just-cached prefix), release its table references
        and requeue its request at the queue HEAD (it is older than
        anything never admitted), to be recomputed on readmission.
        Refcounts keep any block a SURVIVOR also maps alive."""
        st = self._slots[slot]
        self._publish_row(slot)
        self._release_row(slot)
        self._slots[slot] = None
        self._done[slot] = True
        self._remaining[slot] = 0
        self._tok[slot] = 0
        self._pos[slot] = 0
        self._start[slot] = 0
        self._queue.appendleft(st["req"])
        req = st["req"]
        req.timings.preemptions += 1
        req.last_queued_at = req.watch.elapsed()
        self._n_preempted += 1
        self._m_preempted.inc()
        self._m_queue_depth.set(len(self._queue))
        observability.instant(
            "serve/preempt", id=req.id,
            **self._trace_args(req.trace_id),
        )

    def _ensure_blocks(self, slot: int, need: int) -> bool:
        """Grow ``slot``'s table to >= ``need`` blocks, preempting the
        youngest occupant whenever the pool is dry.  Returns False when
        the starved slot was itself the youngest and got preempted
        (its request is back in the queue)."""
        row = self._row_blocks[slot]
        while len(row) < need:
            blk = self._alloc_for(slot)
            if blk is None:
                return False
            self._tables[slot, len(row)] = blk
            row.append(blk)
        self._update_pool_gauges()
        return True

    def _shared(self, blk: int) -> bool:
        """A block this row must NOT write into: other tables still
        reference it, or the prefix cache does (a write would corrupt
        content a future lookup trusts)."""
        return self._ref[blk] > 1 or blk in self._block_hash

    def _cow_split(self, slot: int, j: int, *, copy: bool) -> bool:
        """Copy-on-write: retarget table entry ``j`` of ``slot`` to a
        fresh private block before a write into a shared/cached block.
        ``copy=False`` when the impending write rewrites the whole
        block (a prefill chunk re-run) — the fresh block needs no
        content.  No-op for private blocks.  False when allocation had
        to preempt ``slot`` itself."""
        blk = int(self._row_blocks[slot][j])
        if not self._shared(blk):
            return True
        new = self._alloc_for(slot)
        if new is None:
            return False
        if copy:
            self._pools = self._timed_program(
                ("cow", self.block_size),
                _cow_copy_prog,
                self._pools, jnp.int32(blk), jnp.int32(new),
            )
        self._decref(blk)
        self._row_blocks[slot][j] = new
        self._tables[slot, j] = new
        self._n_cow += 1
        self._update_pool_gauges()
        return True

    # -- the prefix cache -------------------------------------------------

    def _chain_hashes(self, tokens: np.ndarray):
        """This pool's view of the shared keying scheme (see
        :func:`_chain_digests`): raw digests at this engine's block
        size."""
        yield from _chain_digests(tokens, self.block_size)

    def prefix_probe(self, prompt) -> Dict:
        """Paged probe: the prompt's chained block keys plus how many
        lead blocks are CURRENTLY resident in this engine's prefix
        cache (``cached_blocks`` is the longest cached chain prefix —
        exactly what admission would map).  Advisory: the cache mutates
        every tick, so the count is a snapshot, not a reservation.
        Safe to call from any thread (dict lookups only, no
        iteration)."""
        p = np.asarray(prompt, np.int32).reshape(-1)
        keys: List[str] = []
        cached = 0
        walking = self.prefix_cache
        for h in _chain_digests(p, self.block_size):
            keys.append(h.hex())
            if walking and h in self._cache:
                cached += 1
            else:
                walking = False
        return {
            "prefix_cache": self.prefix_cache,
            "block_size": self.block_size,
            "block_keys": keys,
            "cached_blocks": cached,
            "cached_tokens": cached * self.block_size,
        }

    def _lookup_prefix(self, req: Request) -> List[int]:
        """Longest cached block-chain prefix of the request's prompt
        (full blocks only — a divergence mid-block misses from that
        block on).  Claim-free: the caller bumps refcounts when it
        binds.  The hash chain is memoized on the request (content-
        pure); only the hash -> block resolution reads live state."""
        hits: List[int] = []
        if not self.prefix_cache:
            return hits
        if req.digests is None:
            req.digests = list(self._chain_hashes(req.prompt))
        for h in req.digests:
            blk = self._cache.get(h)
            if blk is None:
                break
            hits.append(blk)
        return hits

    def _publish_row(self, slot: int) -> None:
        """Retire/preempt hook: publish this row's COMPLETED full
        blocks (every position holds a real token's K/V) into the
        prefix cache.  First writer wins when two physical blocks hold
        the same content — the duplicate stays private and frees
        normally at release."""
        if not self.prefix_cache:
            return
        st = self._slots[slot]
        req = st["req"]
        emitted = st.get("emitted") or []
        if st["mode"] == "prefill":
            covered = min(
                st["chunks_done"] * self.block_size, req.prompt.size
            )
        else:
            # contiguous K/V coverage: the whole prompt plus every
            # emitted token EXCEPT the last (sampled, never fed back,
            # so its K/V was never written)
            covered = req.prompt.size + max(len(emitted) - 1, 0)
        row = self._row_blocks[slot]
        n_full = min(covered // self.block_size, len(row))
        if not n_full:
            return
        toks = np.concatenate(
            [req.prompt, np.asarray(emitted, np.int32)]
        )[: n_full * self.block_size]
        for j, h in enumerate(self._chain_hashes(toks)):
            blk = int(row[j])
            if h in self._cache or blk in self._block_hash:
                continue  # already published (a mapped prefix), or dup
            self._cache[h] = blk
            self._block_hash[blk] = h

    def flush_prefix_cache(self) -> int:
        """Drop every cache entry; cache-only blocks return to the
        free list (blocks live requests still reference just lose their
        hash and free normally at release).  Returns entries dropped."""
        n = len(self._cache)
        self._cache.clear()
        self._block_hash.clear()
        self._free.extend(self._lru)
        self._lru.clear()
        self._update_pool_gauges()
        return n

    # -- admission: chunked prefill ---------------------------------------

    def _admit_pending(self) -> None:
        # bind a queued request only when the pool can already hold the
        # UNCACHED part of its prompt beyond what in-flight prefills are
        # still owed (a prefix-cache hit consumes no allocation — the
        # blocks are already resident).  A fresh binding always carries
        # the youngest seq, so it can never evict anyone — prefilling
        # before the blocks exist would just starve, self-preempt and
        # requeue every tick, burning prefill compute and inflating the
        # preemption counter for no progress.
        # owed == 0 with the row still in prefill mode is exactly the
        # fully-cached case: its final chunk will COW-split one block
        reserved = sum(
            max(
                s["req"].bucket // self.block_size
                - len(self._row_blocks[i]),
                1,
            )
            for i, s in enumerate(self._slots)
            if s is not None and s["mode"] == "prefill"
        )
        for slot in range(self.batch_size):
            if self._slots[slot] is None and self._queue:
                req = self._queue[0]
                hits = self._lookup_prefix(req)
                # a fully-cached prompt still COW-reruns its final
                # block's chunk for the first-token logits
                need = max(req.bucket // self.block_size - len(hits), 1)
                # allocatable = free + evictable cache, NOT counting the
                # hit blocks themselves (binding pins them)
                pool = (
                    len(self._free)
                    + len(self._lru)
                    - sum(1 for b in hits if b in self._lru)
                )
                if pool - reserved < need:
                    break
                reserved += need
                self._start_prefill(slot, self._queue.popleft(), hits)
        self._m_queue_depth.set(len(self._queue))
        self._m_active.set(self.active)

    def _start_prefill(
        self, slot: int, req: Request, hits: Optional[List[int]] = None
    ) -> None:
        """Bind a queued request to a slot: claim the longest cached
        block-chain prefix of its prompt (refcount bumps pin the blocks
        under the binder) and queue only the UNCACHED tail for chunked
        prefill.  Tail blocks are allocated and chunks run lazily by
        :meth:`_prefill_tick`, so binding itself can never stall or
        starve anyone.  Prompts anchor at position 0 and RIGHT-pad the
        final chunk to the block boundary — the prefix-cache alignment
        contract (see :func:`~znicz_tpu.workflow.generate
        .paged_prefill_chunk`)."""
        req.timings.queue_s += req.watch.elapsed() - req.last_queued_at
        size = req.prompt.size
        tokens = np.full((1, req.bucket), self.pad_id, np.int32)
        tokens[0, :size] = req.prompt
        row = self._row_blocks[slot]
        if hits is None:
            # _admit_pending passes its own lookup through (nothing can
            # mutate the cache in between); this walk serves direct
            # white-box callers only
            hits = self._lookup_prefix(req)
        for j, blk in enumerate(hits):
            self._incref(blk)
            if blk in self._lru:
                del self._lru[blk]
            self._tables[slot, j] = blk
            row.append(blk)
        # a fully-cached prompt still needs its first-token LOGITS: the
        # final block's chunk re-runs (the write guard COW-splits it off
        # the shared block), so at least one chunk always executes
        skip = (
            len(hits) - 1
            if hits and len(hits) * self.block_size == size
            else len(hits)
        )
        req.timings.cached_tokens += skip * self.block_size
        if self.prefix_cache:
            n_lookup = size // self.block_size
            self._n_prefix_hits += len(hits)
            self._n_prefix_misses += n_lookup - len(hits)
            self._n_cached_tokens += skip * self.block_size
            self._m_prefix_hits.inc(len(hits))
            self._m_prefix_misses.inc(n_lookup - len(hits))
            self._m_prefix_tokens.inc(skip * self.block_size)
        self._slots[slot] = {
            "req": req, "emitted": [], "mode": "prefill",
            "seq": self._n_admits, "tokens": tokens,
            "chunks_done": skip,
        }
        self._n_admits += 1
        self._done[slot] = True
        self._remaining[slot] = 0
        self._update_pool_gauges()

    def _prefill_tick(self) -> None:
        """Prompt chunks for prefilling slots, oldest first, under a
        per-tick TOKEN budget (Sarathi-style): the run loop alternates
        this with a decode chunk, so admission steals at most
        ``prefill_budget`` tokens' worth of tower work from the batch
        between decode chunks — a 2048-token prompt admits across a few
        bounded ticks instead of stalling everyone for one monolithic
        prefill.  Budget goes to the OLDEST prefill first (it finishes
        soonest and starts decoding).  While NOTHING is decoding there
        is nobody to stall, so the budget is waived and chunks run
        back-to-back."""
        budget = self.prefill_budget
        for slot in self._slots_by_age():
            while budget > 0 or self.active == 0:
                if not self._prefill_chunk_for(slot):
                    break
                budget -= self.block_size
        self._m_active.set(self.active)

    def _prefill_chunk_for(self, slot: int) -> bool:
        """Run one prefill chunk for ``slot``; True while the slot
        remains in prefill mode (False once admitted, retired,
        preempted, or idle)."""
        st = self._slots[slot]
        if st is None or st["mode"] != "prefill":
            return False  # preempted mid-tick, or already decoding
        faults.fire("engine.prefill")
        req = st["req"]
        size = req.prompt.size
        c = st["chunks_done"]
        if not self._ensure_blocks(slot, c + 1):
            return False  # starved AND youngest: requeued itself
        # a prefill chunk rewrites its whole block: when the target is
        # a mapped cached block (the fully-cached-prompt re-run for
        # first-token logits) COW-split it — copy-free, every slot is
        # about to be overwritten — so shared content stays pristine
        if not self._cow_split(slot, c, copy=False):
            return False
        last = c == req.bucket // self.block_size - 1
        # FIRST admission only: a preemption-recompute's final chunk
        # traces as serve/prefill and re-fires nothing, keeping the
        # one-serve/admit-span-per-request invariant (and the
        # admitted/TTFT series) exact under preemption
        first_time = req.id not in self._admitted_ids
        greedy, top_k, nucleus = self._structure
        t0 = time.perf_counter()
        # the LAST chunk is the admission event (first token sampled);
        # earlier chunks trace as serve/prefill
        with self.timer.phase(
            "admit" if last and first_time else "prefill",
            request=req.id, bucket=req.bucket, chunk=c,
            **self._trace_args(req.trace_id),
        ):
            key = jax.random.fold_in(self._rng, st["seq"])
            self._pools, first = self._timed_program(
                ("prefill", self.block_size, self._structure),
                _paged_prefill_prog,
                self.params, self._pools,
                jnp.asarray(self._tables[slot]),
                jnp.asarray(
                    st["tokens"][
                        :, c * self.block_size:(c + 1) * self.block_size
                    ]
                ),
                jnp.int32(c * self.block_size),
                jnp.zeros((1,), jnp.int32),
                jnp.int32(
                    (size - 1) % self.block_size
                    if last
                    else self.block_size - 1
                ),
                self._temperature, self._top_p, key,
                block_size=self.block_size, n_heads=self.n_heads,
                greedy=greedy, top_k=top_k, nucleus=nucleus,
                moe_top_k=self.moe_top_k,
                moe_dispatch=self.moe_dispatch,
            )
            st["chunks_done"] = c + 1
            if last:
                first = int(first)  # host sync only at admission
        req.timings.prefill_s += time.perf_counter() - t0
        self._m_prefill_chunks.inc()
        if not last:
            return True
        if first_time:
            self._admitted_ids.add(req.id)
            req.ttft_s = req.watch.elapsed()
            self._m_admitted.inc()
            self._m_ttft.observe(req.ttft_s)
        if first == self.eos_id:
            self._retire_slot(slot, [first], "eos")
        elif req.max_new_tokens == 1:
            self._retire_slot(slot, [first], "budget")
        else:
            st["mode"] = "decode"
            st["emitted"] = [first]
            self._tok[slot] = first
            self._pos[slot] = size
            self._start[slot] = 0
            self._done[slot] = False
            self._remaining[slot] = req.max_new_tokens - 1
        return False

    def _retire_slot(self, slot: int, emitted: List[int], reason: str):
        self._slots[slot]["emitted"] = emitted
        self._publish_row(slot)
        self._retire(self._slots[slot]["req"], emitted, reason)
        self._release_row(slot)
        self._slots[slot] = None
        self._done[slot] = True
        self._remaining[slot] = 0
        # zero the stale row state so an idle slot can never index past
        # a narrowed decode window
        self._tok[slot] = 0
        self._pos[slot] = 0
        self._start[slot] = 0

    def _abort_slot(self, slot: int, reason: str) -> None:
        """Paged out-of-band retirement rides the normal retire hook:
        completed full blocks publish to the prefix cache (their K/V is
        valid — a cancelled request's prefix is still reusable) and
        every table reference is released, so the blocks are
        reclaimable the moment the typed completion exists."""
        st = self._slots[slot]
        self._retire_slot(slot, list(st.get("emitted") or []), reason)
        self._m_active.set(self.active)

    # -- the serving loop -------------------------------------------------

    @property
    def active(self) -> int:
        return sum(
            1 for s in self._slots
            if s is not None and s["mode"] == "decode"
        )

    @property
    def prefilling(self) -> int:
        return sum(
            1 for s in self._slots
            if s is not None and s["mode"] == "prefill"
        )

    def _has_work(self) -> bool:
        return bool(self._queue) or self.active > 0 or self.prefilling > 0

    def _grow_for_chunk(self, steps_for) -> bool:
        """Pre-chunk allocation + write guard, oldest first: each
        decoding row gets blocks covering the ``steps_for(slot)``
        positions the coming chunk may write — never the whole budget
        up front; exhaustion preempts the youngest occupant.  A write
        must never land in a shared/cached block: COW-split (with copy
        — the block holds earlier positions' live K/V) any write-range
        block still shared.  Structurally unreachable under
        block-aligned sharing + publish-at-retire (mapped blocks are
        full, writes land past them), but the guard keeps the invariant
        under ANY future publish policy.  Returns False when pressure
        preempted every decoder."""
        for slot in self._slots_by_age():
            st = self._slots[slot]
            if st is None or st["mode"] != "decode":
                continue
            p0 = int(self._pos[slot])
            last_pos = p0 + max(int(steps_for(slot)) - 1, 0)
            if not self._ensure_blocks(
                slot, last_pos // self.block_size + 1
            ):
                continue  # starved AND youngest: requeued itself
            for j in range(
                p0 // self.block_size, last_pos // self.block_size + 1
            ):
                if self._slots[slot] is None:
                    break  # a COW allocation preempted this very row
                if not self._cow_split(slot, j, copy=True):
                    break
        return self.active > 0

    def _decode_window(self) -> int:
        """The decode/verify gather WINDOW: the x2 rung covering the
        blocks active rows actually hold — the compiled-variant count
        stays logarithmic and short requests never pay ``T_max``-wide
        attention (docs/SERVING.md)."""
        need = max(
            (len(self._row_blocks[i]) for i, s in enumerate(self._slots)
             if s is not None and s["mode"] == "decode"),
            default=1,
        )
        window = 1
        while window < need:
            window *= 2
        return min(window, self.blocks_per_row)

    # -- speculative decoding: draft -> verify -> accept -> rollback ------

    def _draft_pending(self) -> Dict[int, np.ndarray]:
        """One drafting pass over the decoding rows: each row's drafter
        context is its OWN prompt plus everything it has emitted (so
        self-repeating generations draft well, not just repetitive
        prompts), clamped so accepted drafts can never outrun the
        row's remaining budget.  Returns {} when NO row drafted —
        the tick then runs the plain decode chunk instead of paying
        for an all-pad verify."""
        drafts: Dict[int, np.ndarray] = {}
        any_draft = False
        for slot, st in enumerate(self._slots):
            if st is None or st["mode"] != "decode":
                continue
            req = st["req"]
            rem = req.max_new_tokens - len(st["emitted"])
            k = min(self.spec_k, rem - 1)
            d = np.zeros((0,), np.int32)
            if k > 0:
                ctx = np.concatenate(
                    [req.prompt, np.asarray(st["emitted"], np.int32)]
                )
                d = np.asarray(
                    self.drafter.propose(ctx, k), np.int32
                ).reshape(-1)[:k]
            drafts[slot] = d
            any_draft = any_draft or d.size > 0
        return drafts if any_draft else {}

    def _verify_chunk(self, drafts: Dict[int, np.ndarray]) -> None:
        """One speculative tick: pack every decoding row's last token +
        drafted continuation into a [B, W] verify batch (W = the
        drafted max snapped UP the ``spec_buckets`` ladder — accepted
        and drafted lengths are traced, so no stream ever compiles a
        program per length), run ONE bucketed verify program, emit each
        row's longest agreeing prefix plus the bonus token, and ROLL
        BACK the rest by truncating the block table — refcounts reclaim
        the rejected blocks, no copies (docs/SERVING.md "Speculative
        decoding")."""
        w = bucket_for(
            max(d.size for d in drafts.values()) + 1, self.spec_buckets
        )
        b = self.batch_size
        tokens = np.full((b, w), self.pad_id, np.int32)
        n_write = np.zeros((b,), np.int32)
        draft_len = np.zeros((b,), np.int32)
        for slot, d in drafts.items():
            st = self._slots[slot]
            req = st["req"]
            rem = req.max_new_tokens - len(st["emitted"])
            dl = min(d.size, w - 1, max(rem - 1, 0))
            tokens[slot, 0] = self._tok[slot]
            tokens[slot, 1:1 + dl] = d[:dl]
            draft_len[slot] = dl
            # only positions 0..dl are ever READ back (t0 + accepted
            # drafts; the bonus token's K/V is the next tick's write):
            # masking the bucket pad in-program both avoids garbage
            # writes and keeps _grow_for_chunk from allocating — and
            # possibly preempting a younger row for — blocks that this
            # same tick's rollback would hand straight back
            n_write[slot] = dl + 1
        if not self._grow_for_chunk(lambda slot: int(n_write[slot])):
            return  # allocation pressure preempted every decoder
        self._peak_active = max(self._peak_active, self.active)
        window = self._decode_window()
        residents = [
            s["req"] for s in self._slots
            if s is not None and s["mode"] == "decode"
        ]
        t0 = time.perf_counter()
        with self.timer.phase(
            "verify", active=self.active, width=w,
            **self._decode_trace_args(residents),
        ):
            rng = jax.random.fold_in(
                self._rng, 1 << 20 | self._chunk_idx
            )
            self._chunk_idx += 1
            greedy, top_k, nucleus = self._structure
            pools, out, n_acc = self._timed_program(
                ("spec_verify", w, self.batch_size, window,
                 self._structure),
                _paged_verify_prog,
                self.params, self._pools,
                jnp.asarray(self._tables[:, :window]),
                jnp.asarray(tokens), jnp.asarray(self._pos),
                jnp.asarray(self._start), jnp.asarray(self._done),
                jnp.asarray(n_write), jnp.asarray(draft_len),
                self._temperature, self._top_p, rng,
                width=w, block_size=self.block_size,
                n_heads=self.n_heads, greedy=greedy, top_k=top_k,
                nucleus=nucleus, moe_top_k=self.moe_top_k,
                moe_dispatch=self.moe_dispatch,
            )
            self._pools = pools
            out = np.asarray(out)
            n_acc = np.asarray(n_acc)
        dt = time.perf_counter() - t0
        self._n_verify_steps += 1
        for r in residents:
            r.timings.decode_s += dt
        for slot, st in enumerate(self._slots):
            # rows preempted during allocation never reached the
            # program (their writes were masked via the done flag)
            if st is None or st["mode"] != "decode":
                continue
            req, emitted = st["req"], st["emitted"]
            dl = int(draft_len[slot])
            na = min(int(n_acc[slot]), dl)
            reason = None
            appended = 0
            for t in out[slot, :na + 1]:
                emitted.append(int(t))
                appended += 1
                if int(t) == self.eos_id:
                    reason = "eos"
                    break
                if len(emitted) >= req.max_new_tokens:
                    reason = "budget"
                    break
            self._n_spec_drafted += dl
            self._n_spec_accepted += na
            self._n_spec_rejected += dl - na
            req.timings.spec_drafted += dl
            req.timings.spec_accepted += na
            if dl:
                self._m_spec_drafted.inc(dl)
                self._m_spec_accepted.inc(na)
                self._m_spec_rejected.inc(dl - na)
                self._m_spec_accept_len.observe(float(na))
            if reason is not None:
                self._retire_slot(slot, emitted, reason)
            else:
                self._tok[slot] = emitted[-1]
                self._pos[slot] = int(self._pos[slot]) + appended
                self._remaining[slot] = req.max_new_tokens - len(emitted)
                self._truncate_row(slot)
        self._m_active.set(self.active)

    def _truncate_row(self, slot: int) -> None:
        """Speculative ROLLBACK: drop the table entries past the last
        position holding accepted K/V.  The truncated blocks were
        allocated (private, COW-guarded) for draft positions the
        verifier rejected — a decref walks each back to the free list
        (or the cache, had it been shared), so rollback is bookkeeping
        only: no device copies, no recompute."""
        row = self._row_blocks[slot]
        keep = (int(self._pos[slot]) - 1) // self.block_size + 1
        if len(row) <= keep:
            return
        for blk in reversed(row[keep:]):
            self._decref(blk)
        del row[keep:]
        self._tables[slot, keep:] = NULL_BLOCK
        self._update_pool_gauges()

    def _run_chunk(self) -> None:
        faults.fire("engine.decode_step")
        if self.spec_k:
            drafts = self._draft_pending()
            if drafts:
                self._last_chunk_kind = "spec_verify"
                self._verify_chunk(drafts)
                return
            # no row produced a draft this tick: fall through to the
            # plain (already-compiled) decode chunk — an unpredictable
            # stream pays ZERO verify overhead and ZERO new programs
        # lazy per-chunk allocation, oldest first: each decoding row
        # gets blocks covering the positions THIS chunk can write
        # (min(chunk, remaining) steps) — never the whole budget up
        # front; exhaustion preempts the youngest occupant
        if not self._grow_for_chunk(
            lambda slot: min(self.admit_every, int(self._remaining[slot]))
        ):
            return  # allocation pressure preempted every decoder
        self._peak_active = max(self._peak_active, self.active)
        # decode WINDOW (:meth:`_decode_window`): allocation above
        # already covers this chunk's growth, so the window cannot be
        # outrun mid-chunk; retired/idle rows were zeroed and write to
        # the null block regardless.
        window = self._decode_window()
        residents = [
            s["req"] for s in self._slots
            if s is not None and s["mode"] == "decode"
        ]
        t0 = time.perf_counter()
        with self.timer.phase(
            "decode", active=self.active,
            **self._decode_trace_args(residents),
        ):
            rng = jax.random.fold_in(self._rng, 1 << 20 | self._chunk_idx)
            self._chunk_idx += 1
            greedy, top_k, nucleus = self._structure
            (pools, tok, pos, done, remaining, out, steps) = (
                self._timed_program(
                    ("paged_chunk", self.admit_every, self.batch_size,
                     window, self._structure),
                    _paged_decode_chunk,
                    self.params, self._pools,
                    jnp.asarray(self._tables[:, :window]),
                    jnp.asarray(self._tok), jnp.asarray(self._pos),
                    jnp.asarray(self._start), jnp.asarray(self._done),
                    jnp.asarray(self._remaining), self._temperature,
                    self._top_p, rng, chunk=self.admit_every,
                    block_size=self.block_size, t_max=self.t_max,
                    n_heads=self.n_heads, eos_id=self.eos_id,
                    greedy=greedy, top_k=top_k, nucleus=nucleus,
                    moe_top_k=self.moe_top_k,
                    moe_dispatch=self.moe_dispatch,
                )
            )
            self._pools = pools
            out = np.asarray(out)
            steps = int(steps)
            self._tok = np.array(tok)
            self._pos = np.array(pos)
            self._done = np.array(done)
            self._remaining = np.array(remaining)
        dt = time.perf_counter() - t0
        for r in residents:
            r.timings.decode_s += dt
        for slot, st in enumerate(self._slots):
            if st is None or st["mode"] != "decode":
                continue
            req, emitted = st["req"], st["emitted"]
            reason = None
            for t in out[slot, :steps]:
                emitted.append(int(t))
                if int(t) == self.eos_id:
                    reason = "eos"
                    break
                if len(emitted) >= req.max_new_tokens:
                    reason = "budget"
                    break
            if reason is not None:
                self._retire_slot(slot, emitted, reason)
        self._m_active.set(self.active)

    # -- introspection ----------------------------------------------------

    def compile_stats(self) -> Dict:
        """Paged ledger: one ``("prefill", block_size, structure)``
        entry plus one ``("paged_chunk", chunk, B, window, structure)``
        entry per x2 window rung the stream's occupancy ever reached —
        logarithmic in T_max/block_size, independent of request count —
        cross-checked against the paged programs' jit caches (shared
        process-wide, like the dense ones)."""
        return {
            "programs": dict(self._programs),
            "n_programs": len(self._programs),
            "program_hits": self._program_hits,
            "prefill_jit_entries": _paged_prefill_prog._cache_size(),
            "paged_chunk_jit_entries": _paged_decode_chunk._cache_size(),
            "cow_jit_entries": _cow_copy_prog._cache_size(),
            "spec_verify_jit_entries": _paged_verify_prog._cache_size(),
        }

    @property
    def pool_free_frac(self) -> float:
        """Fraction of the pool still ALLOCATABLE (free list plus
        evictable cache-only blocks) — the one owner of the formula the
        front door's pool-pressure watermark reads."""
        return (len(self._free) + len(self._lru)) / max(
            self.usable_blocks, 1
        )

    def spec_stats(self) -> Dict:
        """The live speculative-decoding report (``stats()["spec"]``):
        drafted/accepted/rejected token tallies, verify-step count and
        the acceptance rate — accepted drafts over drafted, the single
        number that says whether speculation is paying on this
        stream."""
        return {
            "enabled": bool(self.spec_k),
            "k": self.spec_k,
            "buckets": list(self.spec_buckets),
            "drafted": self._n_spec_drafted,
            "accepted": self._n_spec_accepted,
            "rejected": self._n_spec_rejected,
            "verify_steps": self._n_verify_steps,
            "acceptance_rate": round(
                self._n_spec_accepted / max(self._n_spec_drafted, 1), 4
            ),
        }

    def stats(self) -> Dict:
        """Adds the block-pool + prefix-cache view to the base report.
        ``pool_blocks_free`` counts ALLOCATABLE blocks — the free list
        plus evictable cache-only blocks (``pool_blocks_cached``); a
        cached block a live request also maps counts as used."""
        return {
            **super().stats(),
            "pool_blocks": self.usable_blocks,
            "pool_blocks_free": len(self._free) + len(self._lru),
            "pool_blocks_cached": len(self._lru),
            "block_size": self.block_size,
            "block_bytes": self.block_bytes,
            "pool_bytes": self.usable_blocks * self.block_bytes,
            "preemptions": self._n_preempted,
            "prefix_cache": {
                "enabled": self.prefix_cache,
                "entries": len(self._cache),
                "hits": self._n_prefix_hits,
                "misses": self._n_prefix_misses,
                "cached_tokens": self._n_cached_tokens,
                "evictions": self._n_evictions,
                "cow_splits": self._n_cow,
            },
        }
