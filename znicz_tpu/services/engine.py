"""Continuous micro-batching decode engine: the LM serving front-end.

Orca-style continuous batching (PAPERS.md lineage) over the bucketed
decode fast path (:mod:`znicz_tpu.workflow.generate`, docs/SERVING.md):
a request queue coalesces pending prompts into a fixed B-slot batch over
STATIC [B, T_max] KV buffers; when a row retires (EOS or budget), its
slot is re-used by prefilling the next queued prompt into it while the
other rows keep decoding.  Two compiled programs cover any request
stream:

* **admit** — prefill ONE left-padded [1, bucket] prompt into a fresh
  zeroed cache row and scatter it into the batch at the slot index; one
  compile per prompt-length bucket (geometric ladder, so a handful).
* **decode chunk** — up to ``admit_every`` incremental steps for the
  whole batch in one ``lax.while_loop`` (early exit once every row is
  done), with PER-ROW positions (the cache write is vmapped into a
  scatter), so rows at different depths decode together and no prompt
  length or admission pattern ever recompiles it.

Telemetry rides :mod:`znicz_tpu.observability`: admissions, retirements
(by reason), generated tokens and per-(kind, bucket) compiles are
registry counters; queue depth and active slots are gauges; per-request
latency and time-to-first-token are histograms — all visible on
``/metrics`` and in ``status.json``.  Per-instance views stay available
(``latency`` is a bounded :class:`~znicz_tpu.utils.profiling.LatencyStats`
window feeding the shared latency histogram; ``timer`` is a
:class:`~znicz_tpu.observability.PhaseTimer` whose admit/decode phases
also emit tracer spans — one ``serve/admit`` span per request), and
compile counts are introspectable via
:meth:`DecodeEngine.compile_stats`.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from functools import partial
from typing import Deque, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu import observability
from znicz_tpu.utils import profiling
from znicz_tpu.workflow.generate import (
    DEFAULT_PROMPT_BUCKETS,
    _check_sampling_args,
    _params_fingerprint,
    _sample,
    bucket_for,
    decode_step,
    init_kv_cache,
    pack_prompts,
    prefill,
)

# process-wide first-compile ledger backing znicz_serve_compiles_total:
# the jit caches are shared across engines, so a second engine with the
# same (params geometry, program key) compiles NOTHING new and must not
# re-increment the counter.  (jax.clear_caches() invalidates this — the
# counter then under-reports the recompiles; acceptable for a process-
# lifetime first-compile metric.)
_COMPILED_KEYS: set = set()


@dataclasses.dataclass
class Request:
    """One queued generation request: a 1-D prompt with its own budget."""

    id: int
    prompt: np.ndarray  # 1-D int32
    max_new_tokens: int
    bucket: int  # prompt-length bucket it will be admitted at
    watch: profiling.Stopwatch  # started at submit; read at retirement


@dataclasses.dataclass
class Completion:
    """A finished request: prompt + generated tokens plus its serving
    metrics.  ``latency_s`` is submit -> retirement (queue wait
    included — the number a caller actually experiences)."""

    id: int
    tokens: np.ndarray  # prompt + generated, EOS included when hit
    n_new: int
    finish_reason: str  # "eos" | "budget"
    latency_s: float
    tokens_per_sec: float
    bucket: int


def _sample_tok(logits, key, temperature, top_p, *, greedy, top_k, nucleus):
    """Engine twin of the generate() sampler: greedy argmax or the
    shared truncated-softmax ``_sample`` (structural knobs static)."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return _sample(logits, key, temperature, top_k, nucleus, top_p)


@partial(
    jax.jit,
    static_argnames=(
        "n_heads", "greedy", "top_k", "nucleus", "moe_top_k",
        "moe_dispatch",
    ),
    donate_argnums=(1,),
)
def _admit_row(
    params, caches, prompt, start, slot, temperature, top_p, key, *,
    n_heads, greedy, top_k, nucleus, moe_top_k, moe_dispatch,
):
    """Prefill ONE left-padded [1, bucket] prompt into row ``slot`` of
    the batch caches and sample its first token.

    The row is rebuilt from a fresh ZEROED [1, T_max] cache, so the
    previous occupant's K/V cannot leak into the new request (causality
    already guarantees it — a query at position q only attends
    positions <= q, all rewritten by the current occupant — the zeroed
    row makes it true by construction too).  Compiles once per prompt
    bucket (shape-keyed); the slot index is a traced operand."""
    t_max = caches[0]["k"].shape[1]
    row = init_kv_cache(params, 1, t_max, n_heads=n_heads)
    row, logits = prefill(
        params, prompt, row, n_heads=n_heads, start=start,
        moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
    )
    new = []
    for big, r in zip(caches, row):
        new.append(
            {
                "k": jax.lax.dynamic_update_slice(
                    big["k"], r["k"], (slot, 0, 0, 0)
                ),
                "v": jax.lax.dynamic_update_slice(
                    big["v"], r["v"], (slot, 0, 0, 0)
                ),
            }
        )
    first = _sample_tok(
        logits, key, temperature, top_p, greedy=greedy, top_k=top_k,
        nucleus=nucleus,
    )
    return new, first[0]


@partial(
    jax.jit,
    static_argnames=(
        "chunk", "n_heads", "eos_id", "greedy", "top_k", "nucleus",
        "moe_top_k", "moe_dispatch",
    ),
    donate_argnums=(1,),
)
def _decode_chunk(
    params, caches, tok, pos, start, done, remaining, temperature,
    top_p, rng, *, chunk, n_heads, eos_id, greedy, top_k, nucleus,
    moe_top_k, moe_dispatch,
):
    """Up to ``chunk`` decode steps for the whole batch in ONE compiled
    program, exiting early once every row is done.

    Positions are PER-ROW — the cache write is vmapped into a scatter —
    so rows admitted at different times (different prompt lengths,
    different depths) decode together, and NO prompt length or admission
    pattern ever recompiles this program: the zero-recompile core of the
    engine.  Rows already done emit ``eos_id`` and idle in place (their
    clamped cache write is dead — the slot is rebuilt at re-admission).

    Returns (caches, tok, pos, done, remaining, out [B, chunk], steps):
    the host reads ``out[:, :steps]`` to collect emissions and retire
    rows."""
    b = tok.shape[0]
    t_max = caches[0]["k"].shape[1]
    fill = jnp.int32(eos_id)
    out = jnp.full((b, chunk), fill, jnp.int32)

    def step_rows(caches, tok, pos):
        def one(cache_row, t, p, s):
            c1 = jax.tree_util.tree_map(lambda a: a[None], cache_row)
            c2, lg = decode_step(
                params, c1, t[None], p, n_heads=n_heads, start=s[None],
                moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
            )
            return jax.tree_util.tree_map(lambda a: a[0], c2), lg[0]

        return jax.vmap(one)(caches, tok, pos, start)

    def cond(carry):
        i, _, _, _, done, _, _ = carry
        return (i < chunk) & ~jnp.all(done)

    def body(carry):
        i, caches, tok, pos, done, remaining, out = carry
        caches, logits = step_rows(caches, tok, pos)
        nxt = _sample_tok(
            logits, jax.random.fold_in(rng, i), temperature, top_p,
            greedy=greedy, top_k=top_k, nucleus=nucleus,
        )
        nxt = jnp.where(done, fill, nxt)
        remaining = jnp.where(done, remaining, remaining - 1)
        done = done | (nxt == eos_id) | (remaining <= 0)
        out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, i))
        pos = jnp.minimum(pos + 1, t_max - 1)
        return (i + 1, caches, nxt, pos, done, remaining, out)

    i, caches, tok, pos, done, remaining, out = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), caches, tok, pos, done, remaining, out),
    )
    return caches, tok, pos, done, remaining, out, i


class DecodeEngine:
    """Continuous micro-batching front-end over the KV-cache decoder.

    Usage::

        eng = DecodeEngine(params, n_heads=8, eos_id=0, batch_size=8)
        ids = [eng.submit(prompt, max_new_tokens=64) for prompt in reqs]
        completions = eng.run()          # drain the queue
        eng.stats()                      # latency / tokens/s / compiles

    Greedy by default; ``temperature``/``top_k``/``top_p`` select the
    same sampling structures as :func:`generate` (one compiled program
    set per structure).  ``admit_every`` is the admission granularity:
    the batch decodes in chunks of that many steps between retirement
    checks — small values admit sooner, large values sync less."""

    def __init__(
        self,
        params,
        *,
        n_heads: int,
        eos_id: int,
        batch_size: int = 8,
        max_seq: Optional[int] = None,
        prompt_buckets: Sequence[int] = DEFAULT_PROMPT_BUCKETS,
        admit_every: int = 8,
        pad_id: Optional[int] = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        rng: Optional[jax.Array] = None,
        moe_top_k: int = 1,
        moe_dispatch: str = "dense",
    ):
        if batch_size < 1 or admit_every < 1:
            raise ValueError(
                f"want batch_size >= 1 and admit_every >= 1; got "
                f"{batch_size}, {admit_every}"
            )
        max_pos = params[0]["pos"].shape[0]
        self.t_max = int(max_seq or max_pos)
        if self.t_max > max_pos:
            raise ValueError(
                f"max_seq {self.t_max} exceeds the positional table "
                f"({max_pos})"
            )
        top_k, rng = _check_sampling_args(
            params, temperature, top_k, top_p, rng, eos_id
        )
        self.params = params
        self._params_fp = _params_fingerprint(params)
        self.n_heads = n_heads
        self.eos_id = int(eos_id)
        self.pad_id = int(pad_id if pad_id is not None else eos_id)
        self.batch_size = int(batch_size)
        self.prompt_buckets = tuple(prompt_buckets)
        self.admit_every = int(admit_every)
        self.moe_top_k = moe_top_k
        self.moe_dispatch = moe_dispatch
        self._temperature = jnp.float32(temperature)
        self._top_p = jnp.float32(top_p)
        self._rng = rng
        # static sampling structure: one compiled program set per value
        self._structure = (temperature == 0.0, top_k, top_p < 1.0)
        self._caches = init_kv_cache(
            params, self.batch_size, self.t_max, n_heads=n_heads
        )
        b = self.batch_size
        self._tok = np.zeros((b,), np.int32)
        self._pos = np.zeros((b,), np.int32)
        self._start = np.zeros((b,), np.int32)
        self._done = np.ones((b,), bool)  # empty slots idle as done
        self._remaining = np.zeros((b,), np.int32)
        self._slots: List[Optional[dict]] = [None] * b
        self._queue: Deque[Request] = deque()
        self._order: List[Completion] = []
        self.completions: Dict[int, Completion] = {}
        # process-wide registry series (shared across engines: get-or-
        # create); per-instance windows ride LatencyStats / PhaseTimer
        self._m_submitted = observability.counter(
            "znicz_serve_requests_submitted_total",
            "requests accepted into the engine queue",
        )
        self._m_admitted = observability.counter(
            "znicz_serve_requests_admitted_total",
            "requests prefilled into a batch slot",
        )
        self._m_retired = observability.counter(
            "znicz_serve_requests_retired_total",
            "completed requests by finish reason",
            ("reason",),
        )
        self._m_tokens = observability.counter(
            "znicz_serve_tokens_generated_total",
            "generated tokens across all retired requests",
        )
        self._m_compiles = observability.counter(
            "znicz_serve_compiles_total",
            "distinct compiled engine programs by kind and bucket",
            ("kind", "bucket"),
        )
        self._m_program_hits = observability.counter(
            "znicz_serve_program_hits_total",
            "program invocations served from an already-compiled entry",
        )
        self._m_queue_depth = observability.gauge(
            "znicz_serve_queue_depth", "requests waiting for a slot"
        )
        self._m_active = observability.gauge(
            "znicz_serve_active_slots", "batch slots decoding right now"
        )
        self._m_latency = observability.histogram(
            "znicz_serve_request_latency_seconds",
            "submit -> retirement latency per request (queue wait included)",
        )
        self._m_ttft = observability.histogram(
            "znicz_serve_ttft_seconds",
            "submit -> first sampled token per request",
        )
        self.latency = profiling.LatencyStats(
            observe=self._m_latency.observe
        )
        self.timer = observability.PhaseTimer(
            "znicz_serve_phase_seconds",
            help="engine admit/decode host phase seconds",
            span_prefix="serve/",
        )
        self._programs: Dict[tuple, int] = {}
        self._program_hits = 0
        self._next_id = 0
        self._n_admits = 0
        self._chunk_idx = 0
        self._total_new = 0

    # -- request intake ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int) -> int:
        """Queue one prompt (1-D token ids); returns the request id.
        Validated against the static KV capacity at its bucket size, so
        admission can never fail later."""
        p = np.asarray(prompt, np.int32).reshape(-1)
        if p.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"want max_new_tokens >= 1; got {max_new_tokens}")
        bucket = bucket_for(p.size, self.prompt_buckets)
        if bucket + max_new_tokens > self.t_max:
            raise ValueError(
                f"prompt bucket {bucket} (len {p.size}) + max_new_tokens "
                f"{max_new_tokens} exceeds the KV buffer ({self.t_max})"
            )
        rid = self._next_id
        self._next_id += 1
        self._queue.append(
            Request(rid, p, int(max_new_tokens), bucket,
                    profiling.Stopwatch())
        )
        self._m_submitted.inc()
        self._m_queue_depth.set(len(self._queue))
        return rid

    @property
    def pending(self) -> int:
        return len(self._queue)

    @property
    def active(self) -> int:
        return sum(s is not None for s in self._slots)

    # -- the serving loop -------------------------------------------------

    def run(self) -> List[Completion]:
        """Drain the queue: admit into free slots, decode in chunks,
        retire finished rows, re-admit — until every submitted request
        has completed.  Returns this call's completions in retirement
        order (also kept in :attr:`completions` by id)."""
        n0 = len(self._order)
        while self._queue or self.active:
            self._admit_pending()
            if not self.active:
                continue  # everything admitted retired instantly
            self._run_chunk()
        return self._order[n0:]

    def _program(self, key: tuple) -> None:
        """Ledger one executable per key: the compile-count hook's
        ground truth (tests cross-check it against the jit cache).
        Registry mirror: ``znicz_serve_compiles_total{kind,bucket}``
        counts TRUE first compiles per (params geometry, key) across the
        whole process — a second engine with the same geometry rides the
        shared jit caches and adds nothing.  ``key[1]`` is the prompt
        bucket for admits, the chunk size for the decode program."""
        if key in self._programs:
            self._program_hits += 1
            self._m_program_hits.inc()
        else:
            self._programs[key] = 1
            full_key = (self._params_fp, key)
            if full_key not in _COMPILED_KEYS:
                _COMPILED_KEYS.add(full_key)
                self._m_compiles.labels(kind=key[0], bucket=key[1]).inc()

    def _admit_pending(self) -> None:
        for slot in range(self.batch_size):
            # keep pulling from the queue until the slot holds an ACTIVE
            # row: a request that retires at admission itself (first
            # token is EOS, or budget 1) must not idle the slot for a
            # whole decode chunk
            while self._queue and self._slots[slot] is None:
                self._admit_into(slot, self._queue.popleft())
        self._m_queue_depth.set(len(self._queue))
        self._m_active.set(self.active)

    def _admit_into(self, slot: int, req: Request) -> None:
        with self.timer.phase("admit", request=req.id, bucket=req.bucket):
            tokens, start = pack_prompts(
                [req.prompt], req.bucket, self.pad_id
            )
            self._program(("admit", req.bucket, self._structure))
            key = jax.random.fold_in(self._rng, self._n_admits)
            self._n_admits += 1
            greedy, top_k, nucleus = self._structure
            self._caches, first = _admit_row(
                self.params, self._caches, tokens, start,
                jnp.int32(slot), self._temperature, self._top_p, key,
                n_heads=self.n_heads, greedy=greedy, top_k=top_k,
                nucleus=nucleus, moe_top_k=self.moe_top_k,
                moe_dispatch=self.moe_dispatch,
            )
            first = int(first)
        self._m_admitted.inc()
        self._m_ttft.observe(req.watch.elapsed())
        if first == self.eos_id:
            self._retire(req, [first], "eos")
        elif req.max_new_tokens == 1:
            self._retire(req, [first], "budget")
        else:
            self._slots[slot] = {"req": req, "emitted": [first]}
            self._tok[slot] = first
            self._pos[slot] = req.bucket
            self._start[slot] = req.bucket - req.prompt.size
            self._done[slot] = False
            self._remaining[slot] = req.max_new_tokens - 1

    def _run_chunk(self) -> None:
        with self.timer.phase("decode", active=self.active):
            rng = jax.random.fold_in(self._rng, 1 << 20 | self._chunk_idx)
            self._chunk_idx += 1
            greedy, top_k, nucleus = self._structure
            self._program(
                ("chunk", self.admit_every, self.batch_size,
                 self._structure)
            )
            (caches, tok, pos, done, remaining, out, steps) = _decode_chunk(
                self.params, self._caches, jnp.asarray(self._tok),
                jnp.asarray(self._pos), jnp.asarray(self._start),
                jnp.asarray(self._done), jnp.asarray(self._remaining),
                self._temperature, self._top_p, rng,
                chunk=self.admit_every, n_heads=self.n_heads,
                eos_id=self.eos_id, greedy=greedy, top_k=top_k,
                nucleus=nucleus, moe_top_k=self.moe_top_k,
                moe_dispatch=self.moe_dispatch,
            )
            self._caches = caches
            # ONE host sync per chunk — the admission granularity; the
            # [B]-sized state and [B, chunk] emissions are tiny next to
            # the device-resident KV buffers
            out = np.asarray(out)
            steps = int(steps)
            # np.array (not asarray): host state stays mutable — asarray
            # of a device array is a read-only view
            self._tok = np.array(tok)
            self._pos = np.array(pos)
            self._done = np.array(done)
            self._remaining = np.array(remaining)
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            req, emitted = st["req"], st["emitted"]
            reason = None
            for t in out[slot, :steps]:
                emitted.append(int(t))
                if int(t) == self.eos_id:
                    reason = "eos"
                    break
                if len(emitted) >= req.max_new_tokens:
                    reason = "budget"
                    break
            if reason is not None:
                self._retire(req, emitted, reason)
                self._slots[slot] = None
                self._done[slot] = True
                self._remaining[slot] = 0
        self._m_active.set(self.active)

    def _retire(self, req: Request, emitted: List[int], reason: str):
        dt = req.watch.elapsed()
        comp = Completion(
            id=req.id,
            tokens=np.concatenate(
                [req.prompt, np.asarray(emitted, np.int32)]
            ),
            n_new=len(emitted),
            finish_reason=reason,
            latency_s=dt,
            tokens_per_sec=len(emitted) / max(dt, 1e-9),
            bucket=req.bucket,
        )
        self._order.append(comp)
        self.completions[req.id] = comp
        # feeds the shared registry histogram via the observe hook
        self.latency.record(dt)
        self._total_new += len(emitted)
        self._m_retired.labels(reason=reason).inc()
        self._m_tokens.inc(len(emitted))

    # -- introspection ----------------------------------------------------

    def compile_stats(self) -> Dict:
        """Compile-count hook: ``programs`` maps each
        ``("admit", bucket, structure)`` / ``("chunk", chunk, B,
        structure)`` key to 1 — one executable per key over the engine's
        lifetime; ``program_hits`` counts invocations that reused one.
        ``*_jit_entries`` are the process-wide jax caches backing them
        (shared across engines: a second engine with the same geometry
        compiles nothing new)."""
        return {
            "programs": dict(self._programs),
            "n_programs": len(self._programs),
            "program_hits": self._program_hits,
            "admit_jit_entries": _admit_row._cache_size(),
            "chunk_jit_entries": _decode_chunk._cache_size(),
        }

    def stats(self) -> Dict:
        """Serving report: completions, generated tokens, the per-request
        latency aggregate, per-phase host timings, and compile counts."""
        return {
            "completed": len(self.completions),
            "generated_tokens": self._total_new,
            "latency": self.latency.summary(),
            "phases": self.timer.summary(),
            **self.compile_stats(),
        }
