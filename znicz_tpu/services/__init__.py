"""Services: plotting, image saving, web status.

Replaces the reference's service stack [SURVEY.md 2.1 "Plotting service",
"Web status"; 2.3 "NN plotters", "Image saver"]: the reference publishes
pickled plotter state over ZMQ to a separate matplotlib process and serves a
tornado dashboard; here plotting renders headless PNGs/CSV in-process (no
remote display exists on a TPU pod host) and the status service writes a
JSON/HTML snapshot per epoch.
"""

from znicz_tpu.services.plotting import (  # noqa: F401
    AccumulatingPlotter,
    MetricsCSVWriter,
    Weights2D,
)
from znicz_tpu.services.engine import (  # noqa: F401
    Completion,
    DecodeEngine,
    PagedDecodeEngine,
)
from znicz_tpu.services.errors import (  # noqa: F401
    EngineClosedError,
    RejectedError,
    RequestTooLargeError,
    SpeculationUnsupportedError,
    retryable,
)
from znicz_tpu.services.frontdoor import (  # noqa: F401
    RequestHandle,
    ServingFrontDoor,
)
from znicz_tpu.services.image_saver import ImageSaver  # noqa: F401
from znicz_tpu.services.publishing import MarkdownReporter  # noqa: F401
from znicz_tpu.services.web_status import StatusWriter  # noqa: F401
