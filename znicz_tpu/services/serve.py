"""``python -m znicz_tpu.services.serve <dir> [port]`` — serve a status
directory over HTTP, with a Prometheus ``/metrics`` endpoint.

The reference runs a live tornado dashboard inside the training process
(``veles/web_status.py``, SURVEY.md 2.1); here serving is decoupled: training
writes ``status.json``/``status.html``/``metrics.prom`` files (StatusWriter)
and this command — or any web server — exposes them.  Any number of viewers,
zero training-side state.

Endpoints beyond the static files:

* ``/metrics`` — Prometheus text exposition.  Prefers the
  ``metrics.prom`` the training process drops into the status directory
  (textfile-collector pattern: the scrape reflects the TRAINING
  process's registry); falls back to this server process's own registry
  when the file is absent (e.g. an in-process DecodeEngine server).
* ``/metrics.json`` — the same data as a JSON snapshot, with the same
  file-first preference (the ``"metrics"`` snapshot StatusWriter embeds
  in ``status.json``), so the two endpoints never contradict each
  other.
* ``/healthz`` — liveness.  Plain 200 for a static status server; when
  a :class:`~znicz_tpu.services.frontdoor.ServingFrontDoor` is attached
  (:func:`build_server`), 200 only while its watchdog reports
  ``running`` — a stalled tick, a failed engine rebuild, or a closed
  door answer 503, so a load balancer stops routing here before
  clients hang.
* ``POST /generate`` — LM serving through the front door: a JSON body
  ``{"prompt": [ids], "max_new_tokens": N, "deadline_s": S?}`` streams
  back newline-delimited JSON (chunked transfer): one ``{"token": t}``
  line per generated token and a final ``{"done": true, ...}`` record
  carrying the typed ``finish_reason``, the client-visible trace id
  (also in the ``X-Znicz-Trace-Id`` response header) and latency.
  Load shedding answers 503 + ``Retry-After``; an impossible request
  400.  A client that disconnects mid-stream gets its request
  CANCELLED — crashed callers cannot pin KV blocks.

Graceful shutdown: :func:`run_server` installs SIGTERM/SIGINT handlers
that drain the front door up to a grace period, shed the rest with
typed rejections, stop the listener, and exit 0.
"""

from __future__ import annotations

import functools
import http.server
import json
import logging
import os
import signal
import sys
import threading
import urllib.parse

from znicz_tpu.observability import get_registry, parse_prometheus_text
from znicz_tpu.services.errors import (
    EngineClosedError,
    RejectedError,
    RequestTooLargeError,
    retry_after_header,
)

logger = logging.getLogger(__name__)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
NDJSON_CONTENT_TYPE = "application/x-ndjson"

_SLO_FALLBACK = None
_SLO_FALLBACK_LOCK = threading.Lock()


def _fallback_slo():
    """Lazy process-local SLO monitor for frontdoor-less servers (the
    static status-dir case): /slo still answers, evaluated over this
    process's registry.  Locked: concurrent first polls on a
    ThreadingHTTPServer must share ONE monitor (and one sample ring)."""
    global _SLO_FALLBACK
    with _SLO_FALLBACK_LOCK:
        if _SLO_FALLBACK is None:
            from znicz_tpu.observability.slo import SLOMonitor

            _SLO_FALLBACK = SLOMonitor()
        return _SLO_FALLBACK


def _snapshot_from_prom(text: str) -> dict:
    """Sample-level JSON view of a Prometheus exposition: ``{sample_name:
    {"type"?: ..., "series": [{"labels": ..., "value": ...}]}}``.
    Histogram families appear as their raw ``_bucket``/``_sum``/
    ``_count`` sample names — a faithful rendering of the file, used
    when ``status.json`` carries no embedded snapshot."""
    parsed = parse_prometheus_text(text)
    out: dict = {}
    for name, labels, value in parsed["samples"]:
        fam = out.setdefault(name, {"series": []})
        fam["series"].append({"labels": labels, "value": value})
    for name, kind in parsed["types"].items():
        if name in out:
            out[name]["type"] = kind
    return out


class HttpJsonMixin:
    """Shared response writers for the repo's HTTP/1.1 surfaces (this
    status/front-door server and the cluster router proxy): explicit
    Content-Length on every non-streaming response, and the chunked
    NDJSON frame writer for token streams.  ONE owner, so the framing
    can never diverge between a replica and the router fronting it."""

    def _chunk(self, obj: dict) -> None:
        data = (json.dumps(obj) + "\n").encode()
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _send_json(self, obj: dict, status: int = 200, headers=None):
        self._send(
            (json.dumps(obj) + "\n").encode(),
            "application/json",
            status=status,
            headers=headers,
        )

    def _send(
        self,
        body: bytes,
        content_type: str,
        status: int = 200,
        headers=None,
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for key, value in (headers or {}).items():
            self.send_header(key, value)
        self.end_headers()
        self.wfile.write(body)


class StatusRequestHandler(
    HttpJsonMixin, http.server.SimpleHTTPRequestHandler
):
    """Static status files + registry export + the serving front door.

    HTTP/1.1 so ``POST /generate`` can stream chunked responses; every
    non-streaming response therefore carries an explicit
    Content-Length (:class:`HttpJsonMixin`)."""

    protocol_version = "HTTP/1.1"

    def __init__(self, *args, frontdoor=None, **kwargs):
        # set BEFORE super().__init__: BaseHTTPRequestHandler handles
        # the request inside its constructor
        self.frontdoor = frontdoor
        super().__init__(*args, **kwargs)

    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            self._do_healthz()
        elif path == "/slo":
            # the front door's rolling judgment when one is attached;
            # a plain status server still answers from a process-local
            # monitor over the live registry
            fd = self.frontdoor
            if fd is not None:
                snap = fd.slo_snapshot()
            else:
                # nothing else samples this monitor, so each poll does:
                # consecutive polls build real rolling windows instead
                # of judging lifetime totals as if they were 60 s old
                mon = _fallback_slo()
                mon.maybe_sample()
                snap = mon.snapshot()
            self._send_json(snap)
        elif path == "/debug/requests":
            fd = self.frontdoor
            if fd is None:
                self._send_json(
                    {"error": "no_engine",
                     "detail": "no serving front door attached"},
                    status=404,
                )
            else:
                self._send_json({"requests": fd.recent_requests()})
        elif path == "/debug/programs":
            # the device/compile ledger: every true first compile with
            # its wall time, cost analysis and memory analysis — the
            # count matches the engine ledger and
            # znicz_serve_compiles_total by construction
            from znicz_tpu.observability import device

            self._send_json(device.ledger_snapshot())
        elif path == "/metrics":
            prom = os.path.join(self.directory, "metrics.prom")
            if os.path.exists(prom):
                with open(prom, "rb") as f:
                    body = f.read()
            else:
                body = get_registry().prometheus_text().encode()
            self._send(body, PROM_CONTENT_TYPE)
        elif path == "/metrics.json":
            snap = self._training_snapshot()
            if snap is None:
                snap = get_registry().snapshot()
            body = json.dumps(snap, indent=2).encode()
            self._send(body, "application/json")
        else:
            super().do_GET()

    def _training_snapshot(self):
        """The training process's snapshot, or None: the ``"metrics"``
        dict embedded in ``status.json`` when present, else a sample-
        level view derived from ``metrics.prom`` — so /metrics.json can
        never describe a different world than /metrics does (both are
        training-file-first, live-registry-last)."""
        status_path = os.path.join(self.directory, "status.json")
        if os.path.exists(status_path):
            try:
                with open(status_path) as f:
                    snap = json.load(f).get("metrics")
                if snap is not None:
                    return snap
            except (OSError, ValueError):
                # a half-written legacy file must not 500 the endpoint
                logger.warning("unreadable %s; trying metrics.prom",
                               status_path)
        prom_path = os.path.join(self.directory, "metrics.prom")
        if os.path.exists(prom_path):
            try:
                with open(prom_path) as f:
                    return _snapshot_from_prom(f.read())
            except (OSError, ValueError):
                logger.warning("unreadable %s; serving live registry",
                               prom_path)
        return None

    def _do_healthz(self) -> None:
        fd = self.frontdoor
        if fd is None:
            self._send(b"ok\n", "text/plain")
            return
        state = fd.watchdog_state()
        body = (json.dumps(state) + "\n").encode()
        self._send(
            body,
            "application/json",
            status=200 if state["state"] == "running" else 503,
        )

    # -- the serving front door -------------------------------------------

    def do_POST(self):  # noqa: N802 — http.server API
        path, _, query = self.path.partition("?")
        if path == "/prefix_probe":
            self._do_prefix_probe()
            return
        if path == "/debug/profile":
            self._do_profile(query)
            return
        if path != "/generate":
            self.send_error(404, "unknown endpoint")
            return
        fd = self.frontdoor
        if fd is None:
            self._send_json(
                {"error": "no_engine",
                 "detail": "this server has no serving front door attached"},
                status=503,
            )
            return
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
            prompt = body["prompt"]
            max_new = int(body.get("max_new_tokens", 16))
            deadline_s = body.get("deadline_s")
            if deadline_s is not None:
                deadline_s = float(deadline_s)
        except (KeyError, TypeError, ValueError) as exc:
            self._send_json(
                {"error": "bad_request", "detail": str(exc)}, status=400
            )
            return
        # trace-context propagation: an inbound X-Znicz-Trace-Id (the
        # cluster router mints one per client request) becomes THIS
        # request's trace id, so the router's route/retry spans and
        # every replica's engine spans share one filterable id —
        # instead of each process minting its own
        inbound_trace = self.headers.get("X-Znicz-Trace-Id")
        if inbound_trace:
            inbound_trace = inbound_trace.strip()[:128] or None
        try:
            handle = fd.submit(
                prompt, max_new, deadline_s=deadline_s,
                trace_id=inbound_trace,
            )
        except RejectedError as exc:
            self._send_json(
                {"error": "rejected", "reason": exc.reason,
                 "detail": str(exc)},
                status=503,
                headers={"Retry-After": retry_after_header(exc)},
            )
            return
        except EngineClosedError as exc:
            self._send_json(
                {"error": "engine_closed", "detail": str(exc)},
                status=503,
                headers={"Retry-After": retry_after_header(exc)},
            )
            return
        except RequestTooLargeError as exc:
            self._send_json(
                {"error": "request_too_large", "detail": str(exc)},
                status=400,
            )
            return
        except (TypeError, ValueError) as exc:
            # malformed prompt (None, ragged/nested lists, non-ints)
            # surfaces from submit()'s array coercion — a client error,
            # never a dropped connection
            self._send_json(
                {"error": "bad_request", "detail": str(exc)}, status=400
            )
            return
        self._stream_generation(fd, handle)

    def _do_profile(self, query: str) -> None:
        """``POST /debug/profile?seconds=N`` — one on-demand
        ``jax.profiler`` device capture, host-span aligned
        (:func:`znicz_tpu.observability.device.capture_profile`).
        Answers the capture directory; 409 while another capture runs,
        400 on a malformed duration."""
        from znicz_tpu.observability import device

        # drain any request body first: HTTP/1.1 keep-alive reuses the
        # socket, and unread body bytes would be parsed as the NEXT
        # request's start line (every other POST handler reads it)
        try:
            n = int(self.headers.get("Content-Length") or 0)
            if n:
                self.rfile.read(n)
        except (TypeError, ValueError):  # znicz-check: disable=ZNC008
            # a garbage Content-Length only matters for keep-alive
            # reuse; the capture itself proceeds either way
            logger.debug("unparseable Content-Length on /debug/profile")
        try:
            qs = urllib.parse.parse_qs(query)
            seconds = float(qs.get("seconds", ["1.0"])[0])
        except (TypeError, ValueError) as exc:
            self._send_json(
                {"error": "bad_request", "detail": str(exc)}, status=400
            )
            return
        try:
            result = device.capture_profile(seconds)
        except ValueError as exc:
            # non-finite duration ("nan"/"inf" parse as floats but
            # cannot time a capture): a client error, answered 400
            self._send_json(
                {"error": "bad_request", "detail": str(exc)}, status=400
            )
            return
        except RuntimeError as exc:
            busy = "already running" in str(exc)
            self._send_json(
                {
                    "error": "profile_busy" if busy
                    else "profiler_unavailable",
                    "detail": str(exc),
                },
                status=409 if busy else 503,
            )
            return
        self._send_json({"ok": True, **result})

    def _do_prefix_probe(self) -> None:
        """``POST /prefix_probe`` ``{"prompt": [ids]}`` — the front
        door's public prefix-cache probe over HTTP: the prompt's
        chained block keys plus this replica's cached-block count.  A
        debugging surface for prefix-affinity routing (compare the
        router's learned index against the replica's actual cache) —
        the router itself never calls it; its index tracks, never
        trusts, replica state."""
        fd = self.frontdoor
        if fd is None:
            self._send_json(
                {"error": "no_engine",
                 "detail": "this server has no serving front door attached"},
                status=503,
            )
            return
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
            probe = fd.prefix_probe(body["prompt"])
        except EngineClosedError as exc:
            self._send_json(
                {"error": "engine_closed", "detail": str(exc)}, status=503
            )
            return
        except (KeyError, TypeError, ValueError) as exc:
            self._send_json(
                {"error": "bad_request", "detail": str(exc)}, status=400
            )
            return
        self._send_json(probe)

    def _stream_generation(self, fd, handle) -> None:
        """Chunked NDJSON token stream; a broken pipe mid-stream
        cancels the request so abandoned work frees its KV blocks."""
        self.send_response(200)
        self.send_header("Content-Type", NDJSON_CONTENT_TYPE)
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("X-Znicz-Trace-Id", handle.id)
        self.end_headers()
        try:
            for tok in handle.tokens():
                self._chunk({"token": int(tok)})
            comp = handle.result(timeout=30.0)
            self._chunk(
                {
                    "done": True,
                    "trace_id": handle.id,
                    "finish_reason": comp.finish_reason,
                    "n_new": comp.n_new,
                    "latency_ms": round(1000.0 * comp.latency_s, 1),
                    "ttft_ms": (
                        round(1000.0 * comp.ttft_s, 1)
                        if comp.ttft_s is not None
                        else None
                    ),
                    # the per-request lifecycle breakdown: queue_s /
                    # prefill_s / decode_s / preemptions / cached_tokens
                    "timings": comp.timings,
                    **(
                        {"error": comp.error}
                        if comp.error is not None
                        else {}
                    ),
                }
            )
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, TimeoutError):
            logger.warning(
                "client gone mid-stream; cancelling %s", handle.id
            )
            fd.cancel(handle.id)

def build_server(
    directory: str = ".",
    port: int = 8080,
    host: str = "127.0.0.1",
    frontdoor=None,
) -> http.server.ThreadingHTTPServer:
    """A ready-to-serve HTTP server; ``port=0`` binds an ephemeral
    port (read it back from ``server.server_address``).  Pass a
    :class:`~znicz_tpu.services.frontdoor.ServingFrontDoor` to enable
    ``POST /generate`` and watchdog-backed ``/healthz``."""
    handler = functools.partial(
        StatusRequestHandler, directory=directory, frontdoor=frontdoor
    )
    return http.server.ThreadingHTTPServer((host, port), handler)


def shutdown_gracefully(server, frontdoor=None, grace_s: float = 5.0):
    """Drain-then-stop, callable from any thread: the front door stops
    intake, drains in-flight requests up to ``grace_s``, sheds the
    remainder with typed rejections, then the listener stops.  Running
    response threads are daemonic (``ThreadingHTTPServer``), and every
    front-door stream has already been resolved by ``close()`` — so
    shutdown cannot hang on a slow client."""
    try:
        if frontdoor is not None:
            frontdoor.close(drain=True, grace_s=grace_s)
        # a recording tracer is flushed and closed AFTER the drain, so
        # the spans of the final requests land in the JSONL file before
        # exit — a SIGTERM rollout must not truncate the trace (ISSUE 7
        # satellite)
        from znicz_tpu.observability import get_tracer

        tracer = get_tracer()
        if tracer.recording:
            tracer.stop()
    except Exception:
        # ZNC013: this runs on the signal handler's shutdown thread —
        # a failed drain must still reach server.shutdown(), or SIGTERM
        # leaves the listener serving forever
        logger.exception("graceful drain failed; stopping the listener")
    try:
        server.shutdown()
    except Exception:
        logger.exception("listener shutdown failed")


def run_server(server, frontdoor=None, grace_s: float = 5.0) -> int:
    """Serve until SIGTERM/SIGINT, then shut down gracefully and
    return 0 (the exit code a process supervisor reads as a clean
    rollout, not a crash)."""

    def _on_signal(signum, frame):
        logger.info(
            "signal %s: graceful shutdown (grace %.1fs)", signum, grace_s
        )
        # serve_forever() must keep running while we drain — shutdown()
        # blocks until the serve loop exits, so do it off-thread
        threading.Thread(
            target=shutdown_gracefully,
            args=(server, frontdoor, grace_s),
            name="graceful-shutdown",
            daemon=True,
        ).start()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)
    server.serve_forever()
    server.server_close()
    return 0


def main(argv=None) -> int:
    """Usage: serve <dir> [port] [host].  Binds loopback by default —
    serving all interfaces (host 0.0.0.0) is an explicit choice."""
    args = list(sys.argv[1:] if argv is None else argv)
    directory = args[0] if args else "."
    port = int(args[1]) if len(args) > 1 else 8080
    host = args[2] if len(args) > 2 else "127.0.0.1"
    server = build_server(directory, port, host)
    print(
        f"serving {directory} at http://{host}:{port}/status.html "
        f"(metrics at /metrics, liveness at /healthz)"
    )
    return run_server(server)


if __name__ == "__main__":
    raise SystemExit(main())
