"""``python -m znicz_tpu.services.serve <dir> [port]`` — serve a status
directory over HTTP.

The reference runs a live tornado dashboard inside the training process
(``veles/web_status.py``, SURVEY.md 2.1); here serving is decoupled: training
writes ``status.json``/``status.html`` files (StatusWriter) and this command
— or any web server — exposes them.  Any number of viewers, zero
training-side state.
"""

from __future__ import annotations

import functools
import http.server
import sys


def main(argv=None) -> int:
    """Usage: serve <dir> [port] [host].  Binds loopback by default —
    serving all interfaces (host 0.0.0.0) is an explicit choice."""
    args = list(sys.argv[1:] if argv is None else argv)
    directory = args[0] if args else "."
    port = int(args[1]) if len(args) > 1 else 8080
    host = args[2] if len(args) > 2 else "127.0.0.1"
    handler = functools.partial(
        http.server.SimpleHTTPRequestHandler, directory=directory
    )
    print(f"serving {directory} at http://{host}:{port}/status.html")
    http.server.ThreadingHTTPServer((host, port), handler).serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())