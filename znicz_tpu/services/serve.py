"""``python -m znicz_tpu.services.serve <dir> [port]`` — serve a status
directory over HTTP, with a Prometheus ``/metrics`` endpoint.

The reference runs a live tornado dashboard inside the training process
(``veles/web_status.py``, SURVEY.md 2.1); here serving is decoupled: training
writes ``status.json``/``status.html``/``metrics.prom`` files (StatusWriter)
and this command — or any web server — exposes them.  Any number of viewers,
zero training-side state.

Endpoints beyond the static files:

* ``/metrics`` — Prometheus text exposition.  Prefers the
  ``metrics.prom`` the training process drops into the status directory
  (textfile-collector pattern: the scrape reflects the TRAINING
  process's registry); falls back to this server process's own registry
  when the file is absent (e.g. an in-process DecodeEngine server).
* ``/metrics.json`` — the same data as a JSON snapshot, with the same
  file-first preference (the ``"metrics"`` snapshot StatusWriter embeds
  in ``status.json``), so the two endpoints never contradict each
  other.
"""

from __future__ import annotations

import functools
import http.server
import json
import logging
import os
import sys

from znicz_tpu.observability import get_registry, parse_prometheus_text

logger = logging.getLogger(__name__)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _snapshot_from_prom(text: str) -> dict:
    """Sample-level JSON view of a Prometheus exposition: ``{sample_name:
    {"type"?: ..., "series": [{"labels": ..., "value": ...}]}}``.
    Histogram families appear as their raw ``_bucket``/``_sum``/
    ``_count`` sample names — a faithful rendering of the file, used
    when ``status.json`` carries no embedded snapshot."""
    parsed = parse_prometheus_text(text)
    out: dict = {}
    for name, labels, value in parsed["samples"]:
        fam = out.setdefault(name, {"series": []})
        fam["series"].append({"labels": labels, "value": value})
    for name, kind in parsed["types"].items():
        if name in out:
            out[name]["type"] = kind
    return out


class StatusRequestHandler(http.server.SimpleHTTPRequestHandler):
    """Static status files + the registry export endpoints."""

    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            prom = os.path.join(self.directory, "metrics.prom")
            if os.path.exists(prom):
                with open(prom, "rb") as f:
                    body = f.read()
            else:
                body = get_registry().prometheus_text().encode()
            self._send(body, PROM_CONTENT_TYPE)
        elif path == "/metrics.json":
            snap = self._training_snapshot()
            if snap is None:
                snap = get_registry().snapshot()
            body = json.dumps(snap, indent=2).encode()
            self._send(body, "application/json")
        else:
            super().do_GET()

    def _training_snapshot(self):
        """The training process's snapshot, or None: the ``"metrics"``
        dict embedded in ``status.json`` when present, else a sample-
        level view derived from ``metrics.prom`` — so /metrics.json can
        never describe a different world than /metrics does (both are
        training-file-first, live-registry-last)."""
        status_path = os.path.join(self.directory, "status.json")
        if os.path.exists(status_path):
            try:
                with open(status_path) as f:
                    snap = json.load(f).get("metrics")
                if snap is not None:
                    return snap
            except (OSError, ValueError):
                # a half-written legacy file must not 500 the endpoint
                logger.warning("unreadable %s; trying metrics.prom",
                               status_path)
        prom_path = os.path.join(self.directory, "metrics.prom")
        if os.path.exists(prom_path):
            try:
                with open(prom_path) as f:
                    return _snapshot_from_prom(f.read())
            except (OSError, ValueError):
                logger.warning("unreadable %s; serving live registry",
                               prom_path)
        return None

    def _send(self, body: bytes, content_type: str) -> None:
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def main(argv=None) -> int:
    """Usage: serve <dir> [port] [host].  Binds loopback by default —
    serving all interfaces (host 0.0.0.0) is an explicit choice."""
    args = list(sys.argv[1:] if argv is None else argv)
    directory = args[0] if args else "."
    port = int(args[1]) if len(args) > 1 else 8080
    host = args[2] if len(args) > 2 else "127.0.0.1"
    handler = functools.partial(StatusRequestHandler, directory=directory)
    print(
        f"serving {directory} at http://{host}:{port}/status.html "
        f"(metrics at /metrics)"
    )
    http.server.ThreadingHTTPServer((host, port), handler).serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
