"""The serving front door: async submit/stream/cancel over the engine.

:class:`DecodeEngine`/:class:`PagedDecodeEngine` are synchronous-tick
LIBRARIES — ``run()`` drains a queue and returns.  Real traffic needs a
SERVICE: callers on many threads submitting concurrently, reading
tokens as they are produced, abandoning requests (crashed client,
user hit stop), and bounded by explicit deadlines and admission
control rather than by hope.  :class:`ServingFrontDoor` is that layer
(the VELES supervisor/graceful-degradation lineage, SURVEY §3.4,
revived as a serving concern):

* **one engine thread** owns the engine and drives it tick by tick
  (admit → prefill chunk → decode chunk — the same programs ``run()``
  uses; the front door adds ZERO compiled programs).  All engine state
  stays single-threaded; callers talk to it through queues.
  Speculative decoding rides this unchanged: a paged engine factory
  built with ``spec_k > 0`` drafts/verifies inside the same tick (a
  watchdog restart rebuilds from the factory, so the spec config — and
  the warm verify programs — survive a crash), every completion's
  ``timings`` carries ``spec_drafted``/``spec_accepted``, and the dense
  backend's factory fails construction with the typed
  :class:`~znicz_tpu.services.errors.SpeculationUnsupportedError`
  before the door ever starts.
* **submit() → handle**: validation runs single-flight BEFORE enqueue
  (:class:`RequestTooLargeError` — a request that can never fit is
  refused at the door, not after queueing).  The handle streams tokens
  incrementally (:meth:`RequestHandle.tokens`) and resolves to a typed
  :class:`~znicz_tpu.services.engine.Completion`
  (:meth:`RequestHandle.result`).
* **admission control / backpressure**: the pending queue is BOUNDED
  (``max_pending``); beyond it — or when the paged KV pool's free
  fraction drops under ``shed_pool_frac`` while a backlog exists —
  submission sheds with a typed :class:`RejectedError` carrying
  ``retry_after_s`` (the HTTP surface maps it to 503 + Retry-After).
* **per-request deadlines**: ``deadline_s`` (relative to submit) is
  checked every tick; an expired request is retired MID-FLIGHT with a
  ``deadline_exceeded`` completion and, on the paged backend, its
  blocks released immediately (the PR 4-5 preemption machinery makes
  reclaim cheap).  Queued requests expire without ever touching the
  engine.
* **cancellation**: ``cancel(id)`` (or ``handle.cancel()``) works
  before admission (dropped from the queue), during decode (typed
  ``cancelled`` completion, blocks reclaimed), and after completion
  (no-op, returns False).  The HTTP layer cancels on client
  disconnect, so a crashed caller cannot pin KV blocks.
* **engine watchdog**: every tick timestamps itself; a tick running
  longer than ``stall_after_s`` flips :meth:`watchdog_state` to
  ``"stalled"`` (``/healthz`` → 503).  An engine-thread EXCEPTION
  fails only the slot-resident requests — each gets a typed ``error``
  completion naming the exception — then the engine is rebuilt from
  the factory (``znicz_serve_watchdog_restarts_total``), engine-queued
  requests are re-admitted, and the pending queue proceeds.  Every
  path ends in a completion + stream sentinel: no hung clients, ever.
* **graceful shutdown**: :meth:`close` stops intake
  (:class:`EngineClosedError`), drains in-flight work up to a grace
  period, then sheds the remainder with typed ``shed`` completions.

Failure taxonomy, watermarks and tuning: docs/SERVING.md "The front
door".  Every failure path above is deterministically testable via
:mod:`znicz_tpu.utils.faults` (tests/test_frontdoor.py exercises each
one).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import os
import queue
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterator, List, Optional, Set

import numpy as np

from znicz_tpu import observability
from znicz_tpu.observability.aggregate import MetricsPusher
from znicz_tpu.observability.collector import (
    TracePusher,
    attach_pusher,
    detach_pusher,
)
from znicz_tpu.observability.slo import FRONTDOOR_TARGETS, SLOMonitor
from znicz_tpu.services.engine import (
    Completion,
    DecodeEngine,
    RequestTimings,
)
from znicz_tpu.services.errors import (
    EngineClosedError,
    RejectedError,
    RequestTooLargeError,  # noqa: F401  — re-export beside the raiser
)
from znicz_tpu.utils import faults, profiling

logger = logging.getLogger(__name__)

# finish_reason values a front-door completion can carry, beyond the
# engine's own "eos"/"budget" (docs/SERVING.md failure taxonomy)
REASON_CANCELLED = "cancelled"
REASON_DEADLINE = "deadline_exceeded"
REASON_ERROR = "error"
REASON_SHED = "shed"

# stream-queue sentinel: completion follows, no more tokens
_DONE = object()
# bounded-wait quantum for "wait forever" paths (ZNC010: every blocking
# primitive in services/ carries a timeout)
_IDLE_GAP_S = 60.0


class RequestHandle:
    """Client-side view of one submitted request.  Thread-safe: any
    thread may stream, wait, or cancel; the engine thread feeds it."""

    def __init__(self, door: "ServingFrontDoor", trace_id: str):
        self._door = door
        self.id = trace_id  # client-visible trace id
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._completion: Optional[Completion] = None

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def completion(self) -> Optional[Completion]:
        """The typed completion once :attr:`done`, else None."""
        return self._completion

    def cancel(self) -> bool:
        """Request cancellation; False when already completed."""
        return self._door.cancel(self.id)

    def tokens(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield generated tokens as the engine produces them,
        terminating when the request completes (for ANY reason — check
        :meth:`result` for the typed outcome).  ``timeout`` bounds the
        SILENCE between consecutive tokens; None waits indefinitely
        (safe: every termination path enqueues the sentinel)."""
        while True:
            try:
                item = self._q.get(
                    timeout=timeout if timeout is not None else _IDLE_GAP_S
                )
            except queue.Empty:
                if timeout is not None:
                    raise TimeoutError(
                        f"request {self.id}: no token within {timeout}s"
                    ) from None
                if self._done.is_set() and self._q.empty():
                    return  # belt-and-braces: never hang past completion
                continue
            if item is _DONE:
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> Completion:
        """Block until the request completes; returns the typed
        :class:`Completion`.  Raises ``TimeoutError`` when ``timeout``
        (seconds) elapses first."""
        if timeout is not None:
            if not self._done.wait(timeout=timeout):
                raise TimeoutError(
                    f"request {self.id} still running after {timeout}s"
                )
        else:
            while not self._done.wait(timeout=_IDLE_GAP_S):
                pass
        assert self._completion is not None
        return self._completion


@dataclasses.dataclass(eq=False)
class _FrontRequest:
    """Front-door bookkeeping for one accepted request."""

    trace_id: str
    prompt: np.ndarray  # 1-D int32
    max_new_tokens: int
    deadline_s: Optional[float]  # relative to submit
    handle: RequestHandle
    watch: profiling.Stopwatch  # started at front-door submit
    engine_id: Optional[int] = None  # set once handed to the engine
    streamed: int = 0  # emitted tokens already pushed to the handle
    tokens: List[int] = dataclasses.field(default_factory=list)
    ttft_s: Optional[float] = None  # first token seen (front-door clock)
    # time spent in the FRONT DOOR's pending queue before the engine
    # took it — added to the completion's queue_s (the engine's own
    # queue accounting starts at engine submit)
    pending_wait_s: float = 0.0


class ServingFrontDoor:
    """Thread-safe serving facade owning a decode engine on a
    dedicated engine thread.

    Usage::

        door = ServingFrontDoor(
            lambda: PagedDecodeEngine(params, n_heads=8, eos_id=0),
            max_pending=64,
        )
        h = door.submit(prompt, max_new_tokens=64, deadline_s=30.0)
        for tok in h.tokens():
            ...                      # stream
        comp = h.result()            # typed Completion
        door.close()                 # drain + shed + stop the thread

    ``engine_factory`` must build a FRESH engine with the same config —
    it runs once at construction and again on every watchdog restart
    (restarts ride the process-wide jit caches, so they recompile
    nothing).  ``engine_queue_limit`` caps how many requests sit in the
    ENGINE's internal queue (default: its batch size); the rest wait in
    the front door's pending queue where deadlines and cancellation are
    applied without touching engine state, and where a watchdog restart
    can re-admit them losslessly."""

    def __init__(
        self,
        engine_factory: Callable[[], DecodeEngine],
        *,
        max_pending: int = 64,
        default_deadline_s: Optional[float] = None,
        shed_pool_frac: float = 0.05,
        stall_after_s: float = 10.0,
        idle_tick_s: float = 0.05,
        engine_queue_limit: Optional[int] = None,
        retry_after_s: float = 1.0,
        name: str = "znicz",
        debug_requests: int = 64,
        slo_targets=None,
        slo_windows_s=None,
        slo_sample_gap_s: float = 5.0,
        aggregator_url: Optional[str] = None,
        instance: Optional[str] = None,
        push_interval_s: float = 15.0,
        collector_url: Optional[str] = None,
        trace_push_interval_s: float = 2.0,
    ):
        if max_pending < 1:
            raise ValueError(f"want max_pending >= 1; got {max_pending}")
        self._factory = engine_factory
        self.max_pending = int(max_pending)
        self.default_deadline_s = default_deadline_s
        self.shed_pool_frac = float(shed_pool_frac)
        self.stall_after_s = float(stall_after_s)
        self.idle_tick_s = float(idle_tick_s)
        self.retry_after_s = float(retry_after_s)
        self.name = name
        self._engine: Optional[DecodeEngine] = engine_factory()
        self.engine_queue_limit = int(
            engine_queue_limit
            if engine_queue_limit is not None
            else self._engine.batch_size
        )
        self._lock = threading.Lock()
        self._pending: "deque[_FrontRequest]" = deque()
        self._inflight: Dict[int, _FrontRequest] = {}  # engine id -> fr
        self._by_id: Dict[str, _FrontRequest] = {}
        self._cancels: Set[str] = set()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._closing = False
        self._closed = False
        self._failed = False
        self._shed_requested = False
        self._pool_free_frac = 1.0
        self._tick_started: Optional[float] = None
        self._last_tick = time.monotonic()
        # per-request ids: a per-door random suffix keeps trace ids
        # unique across restarts of the whole process
        self._ids = itertools.count()
        self._suffix = os.urandom(3).hex()
        # the serving instance name: the metrics-push tag, AND the
        # ``instance`` arg every span this door (and its engine) emits
        # carries — the trace collector's per-instance track key
        self.instance = instance or f"{name}-{self._suffix}"
        self._engine.trace_instance = self.instance
        # /debug/requests ring: the last K request summaries (newest
        # last), appended by the engine thread, read under the lock
        self._recent: "deque" = deque(maxlen=max(int(debug_requests), 1))
        # SLO judgment over the process registry: the engine thread
        # samples it on a bounded cadence so /slo always has rolling
        # windows to evaluate (docs/OBSERVABILITY.md "SLOs")
        slo_kw = {
            "min_sample_gap_s": float(slo_sample_gap_s),
            # default: the client-clock front-door histograms (what
            # znicz-slo --frontdoor gates on), not the engine's own —
            # those start at ENGINE submit and cannot see a deep
            # pending queue or a wedged tick
            "targets": (
                slo_targets
                if slo_targets is not None
                else FRONTDOOR_TARGETS
            ),
        }
        if slo_windows_s is not None:
            slo_kw["windows_s"] = slo_windows_s
        self._slo = SLOMonitor(**slo_kw)
        # pristine baseline at door creation: the very first request's
        # observations must be visible as a DELTA against something
        # (the per-tick sample lands only at the end of a tick)
        self._slo.sample()
        # fleet aggregation: push this process's registry to a
        # MetricsAggregator so N replicas land in one /metrics
        # pusher wiring is all-or-nothing: a bad URL must fail the
        # constructor WITHOUT leaking an already-started background
        # pusher thread (the half-built door is discarded and close()
        # never runs on it)
        self._pusher: Optional[MetricsPusher] = None
        self._trace_pusher: Optional[TracePusher] = None
        try:
            # fleet tracing: push this process's spans to a
            # TraceCollector so N replicas land in one merged Perfetto
            # timeline.  The tracer must be recording for spans to
            # exist at all — start a buffer-only window if the
            # operator has not.  Attached (not constructed):
            # in-process colocations sharing one tracer must share ONE
            # pusher or every span pushes N times
            if collector_url:
                observability.get_tracer().ensure_recording()
                self._trace_pusher = attach_pusher(
                    collector_url,
                    instance=self.instance,
                    interval_s=trace_push_interval_s,
                )
            if aggregator_url:
                self._pusher = MetricsPusher(
                    aggregator_url,
                    instance=self.instance,
                    interval_s=push_interval_s,
                ).start()
        except Exception:
            if self._trace_pusher is not None:
                detach_pusher(self._trace_pusher)
                self._trace_pusher = None
            if self._pusher is not None:
                self._pusher.stop(timeout=0.1)
                self._pusher = None
            raise
        # per-instance tallies (the registry counters are process-wide)
        self._n_submitted = 0
        self._n_completed = 0
        self._n_cancelled = 0
        self._n_deadline = 0
        self._n_shed = 0
        self._n_restarts = 0
        self._n_rejected: Dict[str, int] = {}
        self._m_rejected = observability.counter(
            "znicz_serve_rejected_total",
            "submissions shed at the front door by reason",
            ("reason",),
        )
        self._m_deadline = observability.counter(
            "znicz_serve_deadline_exceeded_total",
            "requests retired because their deadline expired",
        )
        self._m_cancelled = observability.counter(
            "znicz_serve_cancelled_total",
            "requests retired by client cancellation",
        )
        self._m_restarts = observability.counter(
            "znicz_serve_watchdog_restarts_total",
            "engine rebuilds after an engine-thread exception",
        )
        # same family the engine retires into (get-or-create): the
        # front door is the ONLY writer of reason="error" — crash/
        # submit-failed requests bypass the engine's _retire, and
        # /slo's error_rate reads exactly this series; without it a
        # crash incident would be invisible to the SLO gate
        self._m_retired = observability.counter(
            "znicz_serve_requests_retired_total",
            "completed requests by finish reason",
            ("reason",),
        )
        self._m_pending = observability.gauge(
            "znicz_serve_frontdoor_pending",
            "requests waiting in the front-door queue",
        )
        self._m_oldest = observability.gauge(
            "znicz_serve_frontdoor_queue_age_seconds",
            "age of the oldest front-door-queued request",
        )
        self._m_inflight = observability.gauge(
            "znicz_serve_frontdoor_inflight",
            "requests handed to the engine and not yet completed",
        )
        # CLIENT-clock histograms: submit -> first streamed token /
        # completion, front-door queueing and tick cadence included —
        # what the SLO targets judge (the engine's own ttft/latency
        # series start at ENGINE submit and miss both)
        self._m_fd_ttft = observability.histogram(
            "znicz_serve_frontdoor_ttft_seconds",
            "front-door submit -> first streamed token (client clock)",
        )
        self._m_fd_latency = observability.histogram(
            "znicz_serve_frontdoor_latency_seconds",
            "front-door submit -> completion delivery (client clock)",
        )
        # the SLO judgment as ONE routable number: the max burn rate
        # across targets/windows with data, refreshed on the SLO
        # sample cadence.  A per-instance read through the aggregator
        # lets the cluster router steer traffic away from a replica
        # that is burning its error budget (docs/SERVING.md)
        self._m_burn = observability.gauge(
            "znicz_serve_slo_burn_rate",
            "max SLO burn rate across targets and windows with data "
            "(the router load tiebreak's per-instance input)",
        )
        self._thread = threading.Thread(
            target=self._serve_loop, name=f"{name}-frontdoor", daemon=True
        )
        self._thread.start()

    # -- client surface ---------------------------------------------------

    @property
    def engine(self) -> Optional[DecodeEngine]:
        """The CURRENT engine (replaced on watchdog restart)."""
        return self._engine

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        deadline_s: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> RequestHandle:
        """Accept one request; returns its :class:`RequestHandle`.
        Single-flight validation happens HERE (before enqueue):
        malformed input raises ``ValueError``, an impossible request
        :class:`RequestTooLargeError`, a closed door
        :class:`EngineClosedError`, and load shedding
        :class:`RejectedError` — nothing invalid ever occupies a queue
        slot.  ``trace_id`` adopts an INBOUND id (the HTTP surface
        passes ``X-Znicz-Trace-Id`` through; the cluster router mints
        one per client request) so one id threads router → replica →
        engine spans instead of each process minting its own; omitted,
        the door mints as before."""
        try:
            p = np.asarray(prompt, np.int32).reshape(-1)
        except (TypeError, ValueError) as exc:
            raise ValueError(f"malformed prompt: {exc}") from exc
        if p.size == 0:
            raise ValueError("empty prompt")
        n_new = int(max_new_tokens)
        if n_new < 1:
            raise ValueError(f"want max_new_tokens >= 1; got {n_new}")
        if deadline_s is not None:
            # coerce HERE, single-flight: a non-numeric deadline must
            # fail the caller, not poison every engine-thread tick
            try:
                deadline_s = float(deadline_s)
            except (TypeError, ValueError) as exc:
                raise ValueError(
                    f"malformed deadline_s: {exc}"
                ) from exc
            if deadline_s < 0.0:
                raise ValueError(
                    f"want deadline_s >= 0; got {deadline_s}"
                )
        with self._lock:
            if self._closing or self._closed:
                self._reject("closed")
                raise EngineClosedError(
                    "front door is closed to new submissions"
                )
            eng = self._engine
            if eng is None:
                self._reject("engine_down")
                raise EngineClosedError(
                    "engine is down and could not be restarted"
                )
            eng._validate_request(p, n_new)  # RequestTooLargeError
            if len(self._pending) >= self.max_pending:
                self._reject("queue_full")
                raise RejectedError(
                    f"pending queue full ({self.max_pending} requests); "
                    "retry later",
                    reason="queue_full",
                    retry_after_s=self.retry_after_s,
                )
            if (
                self.shed_pool_frac > 0.0
                and self._pending
                and self._pool_free_frac < self.shed_pool_frac
            ):
                self._reject("pool_pressure")
                raise RejectedError(
                    f"KV pool under pressure "
                    f"({self._pool_free_frac:.0%} allocatable < "
                    f"{self.shed_pool_frac:.0%} watermark) with a "
                    "backlog; retry later",
                    reason="pool_pressure",
                    retry_after_s=self.retry_after_s,
                )
            tid = self._mint_id(trace_id)
            handle = RequestHandle(self, tid)
            fr = _FrontRequest(
                trace_id=tid,
                prompt=p,
                max_new_tokens=n_new,
                deadline_s=(
                    deadline_s
                    if deadline_s is not None
                    else self.default_deadline_s
                ),
                handle=handle,
                watch=profiling.Stopwatch(),
            )
            self._pending.append(fr)
            self._by_id[tid] = fr
            self._n_submitted += 1
            self._m_pending.set(len(self._pending))
        observability.instant(
            "frontdoor/submit", id=tid, instance=self.instance
        )
        self._wake.set()
        return handle

    def _mint_id(self, trace_id: Optional[str]) -> str:
        """The request's trace id (lock held by the caller): the
        inbound id verbatim when given and not currently live; a live
        collision keeps the inbound id as a PREFIX (``-r<n>`` suffix)
        so a Perfetto substring filter still finds it; else a minted
        ``<name>-<suffix>-<n>`` id."""
        if trace_id:
            tid = str(trace_id).strip()[:128]
            if tid and tid not in self._by_id:
                return tid
            if tid:
                return f"{tid}-r{next(self._ids):04d}"
        return f"{self.name}-{self._suffix}-{next(self._ids):06d}"

    def cancel(self, trace_id: str) -> bool:
        """Request cancellation of ``trace_id`` — valid before
        admission, during decode, or after completion (then a no-op
        returning False).  Applied by the engine thread at the next
        tick; the handle resolves with a ``cancelled`` completion."""
        with self._lock:
            if trace_id not in self._by_id:
                return False
            self._cancels.add(trace_id)
        self._wake.set()
        return True

    def close(self, *, drain: bool = True, grace_s: float = 5.0) -> None:
        """Graceful shutdown: stop intake immediately (submit raises
        :class:`EngineClosedError`), give in-flight work ``grace_s``
        seconds to drain, then shed whatever remains with typed
        ``shed`` completions and stop the engine thread.  Idempotent."""
        with self._lock:
            already = self._closed
            self._closing = True
        self._wake.set()
        if already and not self._thread.is_alive():
            return
        if drain:
            deadline = time.monotonic() + grace_s
            while time.monotonic() < deadline and self.has_work():
                time.sleep(0.01)
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=grace_s + 30.0)
        if self._thread.is_alive():
            logger.error(
                "front door engine thread failed to stop (stalled tick?)"
            )
        with self._lock:  # submit() reads _closed under the lock
            self._closed = True
        if self._pusher is not None:
            # final flush AFTER the drain: the aggregator's last view of
            # this instance includes the shutdown-path counters
            self._pusher.stop()
        if self._trace_pusher is not None:
            # same contract for spans: the final requests' lifecycle
            # events land in the collector before the door goes away
            # (shared pusher: the LAST detaching component flushes)
            detach_pusher(self._trace_pusher)
            self._trace_pusher = None

    def __enter__(self) -> "ServingFrontDoor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- health / introspection -------------------------------------------

    def has_work(self) -> bool:
        if self._inflight:
            return True
        with self._lock:
            if self._pending or self._cancels:
                return True
        eng = self._engine
        return eng is not None and eng._has_work()

    def watchdog_state(self) -> Dict:
        """Liveness as observed from OUTSIDE the engine thread — the
        ``/healthz`` truth.  ``stalled`` means the current tick has run
        longer than ``stall_after_s`` (a wedged device call, an
        injected slow tick); ``failed`` means the engine could not be
        rebuilt after a crash."""
        now = time.monotonic()
        started = self._tick_started
        if self._closed:
            state = "closed"
        elif self._failed:
            state = "failed"
        elif started is not None and now - started > self.stall_after_s:
            state = "stalled"
        else:
            state = "running"
        return {
            "state": state,
            "last_tick_age_s": round(now - self._last_tick, 3),
            "tick_in_flight_s": (
                round(now - started, 3) if started is not None else 0.0
            ),
            "restarts": self._n_restarts,
            "pending": len(self._pending),
            "inflight": len(self._inflight),
            # the per-replica load signal a cluster router tiebreaks on
            # (rides /healthz, so one heartbeat carries liveness AND
            # load; 1.0 on the dense backend — no pool to run dry)
            "pool_free_frac": round(self._pool_free_frac, 4),
        }

    def prefix_probe(self, prompt) -> Dict:
        """Delegate to the CURRENT engine's public
        :meth:`~znicz_tpu.services.engine.DecodeEngine.prefix_probe`:
        the prompt's chained block keys plus the cached-block count —
        what a prefix-affinity router (or a test) reads instead of
        engine privates.  Advisory snapshot (the engine thread mutates
        the cache between ticks); raises :class:`EngineClosedError`
        when the engine is down."""
        eng = self._engine
        if eng is None:
            raise EngineClosedError(
                "engine is down; nothing to probe"
            )
        return eng.prefix_probe(prompt)

    def healthy(self) -> bool:
        return self.watchdog_state()["state"] == "running"

    def slo_snapshot(self) -> Dict:
        """Rolling SLO judgment (``GET /slo`` body, and the input the
        SLO-aware-scheduling rung consumes): per-target p50/p95/p99 and
        multi-window burn rates over the TTFT/latency histograms, plus
        request/error/shed rates.  Thread-safe — evaluation reads the
        registry and the monitor's sample ring, never engine state."""
        return self._slo.snapshot()

    def recent_requests(self) -> List[Dict]:
        """The ``/debug/requests`` ring: the last K completed request
        summaries, NEWEST FIRST — trace id, finish reason, latency,
        TTFT and the queue/prefill/decode timings breakdown.  Live
        debugging surface; bounded, so safe to poll."""
        with self._lock:
            return list(reversed(self._recent))

    def stats(self) -> Dict:
        """Front-door report: the admission/termination tallies plus
        the live engine's own :meth:`~DecodeEngine.stats`."""
        eng = self._engine
        with self._lock:  # _reject mutates the dict under the lock
            rejected = dict(self._n_rejected)
        return {
            "submitted": self._n_submitted,
            "completed": self._n_completed,
            "rejected": rejected,
            "cancelled": self._n_cancelled,
            "deadline_exceeded": self._n_deadline,
            "shed": self._n_shed,
            "watchdog_restarts": self._n_restarts,
            "watchdog": self.watchdog_state(),
            "engine": eng.stats() if eng is not None else {},
        }

    # -- the engine thread ------------------------------------------------

    def _serve_loop(self) -> None:
        # the WHOLE body runs under the failure handler (ZNC013): a
        # crash anywhere on this thread — has_work touching a dying
        # engine included, not just the tick itself — must become the
        # watchdog's typed restart path, never a silent thread death
        while True:
            try:
                if not self.has_work():
                    self._wake.wait(timeout=self.idle_tick_s)
                self._wake.clear()
                stopping = self._stop.is_set()
                if stopping:
                    self._shed_requested = True
                self._tick()
                if stopping and not self.has_work():
                    break
            except Exception as exc:  # engine-thread failure
                self._engine_failure(exc)
        with self._lock:
            self._closed = True

    def _tick(self) -> None:
        self._tick_started = time.monotonic()
        try:
            faults.fire("frontdoor.slow_tick")
            self._apply_control()
            if self._shed_requested:
                self._shed_all()
            self._pump_pending()
            eng = self._engine
            if eng is not None:
                # one occupancy-instrumented engine tick (admit +
                # prefill + decode/verify chunk); no-op without work
                eng.tick()
            self._stream_and_collect()
            self._publish_gauges()
            if self._slo.maybe_sample():
                # the sample cadence is also the burn-gauge cadence:
                # the router's load tiebreak reads this per-instance
                # through the aggregator (ROADMAP: /slo burn rates in
                # the tiebreak)
                self._publish_burn()
        finally:
            self._last_tick = time.monotonic()
            self._tick_started = None

    def _apply_control(self) -> None:
        """Cancellations and deadline expiry, applied between engine
        ticks (so engine state is only ever touched from this thread)."""
        with self._lock:
            cancels, self._cancels = self._cancels, set()
            # snapshot under the lock: submit() appends concurrently,
            # and iterating a deque mid-append raises (ZNC012)
            pending = list(self._pending)
        eng = self._engine
        for tid in cancels:
            fr = self._by_id.get(tid)
            if fr is None:
                continue  # completed before the cancel landed
            self._terminate(fr, REASON_CANCELLED, eng)
        for fr in [f for f in pending if self._expired(f)]:
            self._terminate(fr, REASON_DEADLINE, eng)
        for fr in [
            f for f in list(self._inflight.values()) if self._expired(f)
        ]:
            self._terminate(fr, REASON_DEADLINE, eng)

    @staticmethod
    def _expired(fr: _FrontRequest) -> bool:
        return (
            fr.deadline_s is not None
            and fr.watch.elapsed() > fr.deadline_s
        )

    def _terminate(
        self,
        fr: _FrontRequest,
        reason: str,
        eng: Optional[DecodeEngine],
    ) -> None:
        """Retire ``fr`` with a typed completion wherever it lives."""
        if fr.engine_id is not None and fr.engine_id in self._inflight:
            comp = (
                eng.abort(fr.engine_id, reason) if eng is not None else None
            )
            if comp is None:
                return  # already completed: the normal path wins
            self._inflight.pop(fr.engine_id, None)
            if eng is not None:
                eng.reap(fr.engine_id)
            self._finish(fr, comp)
        else:
            with self._lock:
                try:
                    self._pending.remove(fr)
                except ValueError:
                    # already terminated this tick (e.g. cancel + expiry
                    # landing together): first writer won
                    logger.debug(
                        "%s already terminated; dropping %s",
                        fr.trace_id, reason,
                    )
                    return
            self._finish(fr, self._local_completion(fr, reason))

    def _pump_pending(self) -> None:
        """Move pending work into the engine, keeping its internal
        queue shallow (``engine_queue_limit``) so most waiting happens
        HERE — where deadlines, cancellation and restart re-admission
        are cheap."""
        eng = self._engine
        if eng is None:
            return
        while True:
            with self._lock:
                if not self._pending or eng.pending >= self.engine_queue_limit:
                    break
                fr = self._pending.popleft()
            fr.pending_wait_s = fr.watch.elapsed()
            try:
                rid = eng.submit(
                    fr.prompt, fr.max_new_tokens, trace_id=fr.trace_id
                )
            except Exception as exc:
                # pre-validated, so only config drift after a restart
                # can land here; typed error, never a hung handle
                self._finish(
                    fr,
                    self._local_completion(
                        fr,
                        REASON_ERROR,
                        error=f"{type(exc).__name__}: {exc}",
                    ),
                )
                continue
            fr.engine_id = rid
            self._inflight[rid] = fr

    def _stream_and_collect(self) -> None:
        """Push newly emitted tokens to each handle's stream and reap
        completions.  A preempted row restarts decode on re-admission
        and streaming resumes past the delivered prefix — exact under
        greedy recompute; with ``temperature > 0`` the resumed suffix
        may diverge (fresh sampling keys; see docs/SERVING.md)."""
        eng = self._engine
        if eng is None:
            return
        for st in eng._slots:
            if st is None:
                continue
            fr = self._inflight.get(st["req"].id)
            if fr is None:
                continue
            emitted = st.get("emitted") or []
            if fr.streamed < len(emitted):
                if fr.streamed == 0:
                    fr.ttft_s = fr.watch.elapsed()
                for t in emitted[fr.streamed:]:
                    fr.tokens.append(int(t))
                    fr.handle._q.put(int(t))
                fr.streamed = len(emitted)
        done = [r for r in self._inflight if r in eng.completions]
        for rid in done:
            fr = self._inflight.pop(rid)
            comp = eng.completions[rid]
            eng.reap(rid)
            self._finish(fr, comp)

    def _finish(self, fr: _FrontRequest, comp: Completion) -> None:
        """The ONE termination path: every accepted request — whatever
        its fate — flows through here exactly once, so every handle
        resolves and every stream ends."""
        comp.trace_id = fr.trace_id
        if len(comp.tokens) < fr.prompt.size + fr.streamed:
            # an abort caught the request REQUEUED after a preemption:
            # the engine's emitted list was dropped at eviction, but the
            # client already received fr.streamed tokens — the typed
            # completion must agree with the stream, not undercount it
            comp.tokens = np.concatenate(
                [fr.prompt, np.asarray(fr.tokens, np.int32)]
            )
            comp.n_new = len(fr.tokens)
            comp.tokens_per_sec = comp.n_new / max(comp.latency_s, 1e-9)
        # tokens that retired inside the final tick (or arrived with an
        # out-of-band abort) and were never streamed
        tail = comp.tokens[fr.prompt.size + fr.streamed:]
        if len(tail) and fr.streamed == 0 and fr.ttft_s is None:
            fr.ttft_s = fr.watch.elapsed()
        for t in tail:
            fr.handle._q.put(int(t))
        if comp.ttft_s is None:
            comp.ttft_s = fr.ttft_s
        # client-clock series (the SLO inputs): only the front-door
        # first-token instant — never the engine's admission-time ttft,
        # which a request aborted after a preemption (tokens reconciled
        # away, nothing ever streamed) would otherwise leak here,
        # recording a tiny engine-clock ttft for a request that sat in
        # the pending queue the whole time.  Client cancels, shutdown
        # sheds and engine-crash errors are not latency measurements —
        # a flood of fast cancels (or a burst of requests error-failed
        # 0.2s in by a crash) mid-incident must not dilute bad_frac
        # below a real breach (those fates are judged via the
        # cancelled/rejected/error rate counters instead; deadline
        # expiries DO count — they are genuinely slow requests)
        if comp.finish_reason not in (
            REASON_CANCELLED, REASON_SHED, REASON_ERROR
        ):
            self._m_fd_latency.observe(fr.watch.elapsed())
        if fr.ttft_s is not None:
            self._m_fd_ttft.observe(fr.ttft_s)
        # every completion carries the lifecycle breakdown: the engine's
        # own accounting plus the FRONT-DOOR pending wait (a request that
        # never reached the engine is pure queue time)
        if comp.timings is None:
            comp.timings = RequestTimings(
                queue_s=fr.watch.elapsed()
            ).as_dict()
        else:
            comp.timings = dict(comp.timings)
            comp.timings["queue_s"] = round(
                comp.timings.get("queue_s", 0.0) + fr.pending_wait_s, 6
            )
        fr.handle._completion = comp
        fr.handle._done.set()
        fr.handle._q.put(_DONE)
        with self._lock:
            self._by_id.pop(fr.trace_id, None)
            self._recent.append(
                {
                    "trace_id": fr.trace_id,
                    "finish_reason": comp.finish_reason,
                    "prompt_len": int(fr.prompt.size),
                    "n_new": comp.n_new,
                    "latency_ms": round(1000.0 * fr.watch.elapsed(), 1),
                    "ttft_ms": (
                        round(1000.0 * comp.ttft_s, 1)
                        if comp.ttft_s is not None
                        else None
                    ),
                    "timings": comp.timings,
                    "error": comp.error,
                    "done_unix": time.time(),  # timestamp, not a delta
                }
            )
        self._n_completed += 1
        if comp.finish_reason == REASON_DEADLINE:
            self._n_deadline += 1
            self._m_deadline.inc()
        elif comp.finish_reason == REASON_CANCELLED:
            self._n_cancelled += 1
            self._m_cancelled.inc()
        elif comp.finish_reason == REASON_SHED:
            self._n_shed += 1
            self._m_rejected.labels(reason="shutdown").inc()
        elif comp.finish_reason == REASON_ERROR:
            self._m_retired.labels(reason="error").inc()
        observability.instant(
            "frontdoor/done",
            id=fr.trace_id,
            reason=comp.finish_reason,
            latency_ms=round(1000.0 * fr.watch.elapsed(), 1),
            instance=self.instance,
        )

    def _local_completion(
        self,
        fr: _FrontRequest,
        reason: str,
        error: Optional[str] = None,
        timings: Optional[RequestTimings] = None,
    ) -> Completion:
        """A typed completion for a request the ENGINE cannot speak for
        (never admitted, or the engine just died).  ``timings`` carries
        the dead engine's real per-request accounting when the request
        HAD been admitted — without it, :meth:`_finish` would fabricate
        a 100%%-queue-wait breakdown for a request that was mid-decode
        when the engine crashed."""
        dt = fr.watch.elapsed()
        return Completion(
            id=fr.engine_id if fr.engine_id is not None else -1,
            tokens=np.concatenate(
                [fr.prompt, np.asarray(fr.tokens, np.int32)]
            ),
            n_new=len(fr.tokens),
            finish_reason=reason,
            latency_s=dt,
            tokens_per_sec=len(fr.tokens) / max(dt, 1e-9),
            bucket=0,
            ttft_s=fr.ttft_s,
            error=error,
            timings=timings.as_dict() if timings is not None else None,
        )

    def _engine_failure(self, exc: Exception) -> None:
        """The watchdog's crash path: collect what completed, fail the
        slot-resident requests with typed error completions, rebuild
        the engine, re-admit engine-queued work.  Restarts ride the
        process-wide jit caches — nothing recompiles."""
        logger.error(
            "engine thread failed; restarting engine", exc_info=exc
        )
        msg = f"{type(exc).__name__}: {exc}"
        eng = self._engine
        try:
            # completions that beat the crash are real — deliver them
            self._stream_and_collect()
        except Exception:
            logger.warning(
                "post-failure completion sweep failed", exc_info=True
            )
        queued_ids: Set[int] = set()
        engine_timings: Dict[int, RequestTimings] = {}
        if eng is not None:
            try:
                queued_ids = {r.id for r in eng._queue}
                # salvage the dead engine's per-request accounting so
                # crash-failed completions report their REAL breakdown
                for r in eng._queue:
                    engine_timings[r.id] = r.timings
                for st in eng._slots:
                    if st is not None:
                        engine_timings[st["req"].id] = st["req"].timings
            except Exception:
                logger.warning(
                    "could not read the failed engine's queue; failing "
                    "all in-flight requests", exc_info=True
                )
        requeue: List[_FrontRequest] = []
        for rid, fr in list(self._inflight.items()):
            if rid in queued_ids and not fr.tokens:
                fr.engine_id = None  # never admitted: recompute losslessly
                requeue.append(fr)
            else:
                self._finish(
                    fr,
                    self._local_completion(
                        fr, REASON_ERROR, error=msg,
                        timings=engine_timings.get(rid),
                    ),
                )
        self._inflight.clear()
        with self._lock:
            for fr in reversed(requeue):
                self._pending.appendleft(fr)
        self._n_restarts += 1
        self._m_restarts.inc()
        try:
            new_engine = self._factory()
        except Exception:
            logger.error(
                "engine factory failed after a crash; front door is "
                "failed-closed", exc_info=True
            )
            with self._lock:
                self._engine = None
                self._closing = True
            self._failed = True
            self._shed_requested = True  # next tick sheds the queue
            return
        new_engine.trace_instance = self.instance
        with self._lock:
            self._engine = new_engine
        self._wake.set()

    def _shed_all(self) -> None:
        """Shutdown shedding: typed ``shed`` completions for everything
        still queued or in flight — the queue never strands a client."""
        eng = self._engine
        with self._lock:
            pending, self._pending = list(self._pending), deque()
        for fr in pending:
            self._finish(fr, self._local_completion(fr, REASON_SHED))
        for rid, fr in list(self._inflight.items()):
            comp = eng.abort(rid, REASON_SHED) if eng is not None else None
            if comp is None:
                comp = self._local_completion(fr, REASON_SHED)
            elif eng is not None:
                eng.reap(rid)
            self._inflight.pop(rid, None)
            self._finish(fr, comp)

    def _publish_gauges(self) -> None:
        eng = self._engine
        with self._lock:
            n = len(self._pending)
            oldest = max(
                (f.watch.elapsed() for f in self._pending), default=0.0
            )
        self._m_pending.set(n)
        self._m_oldest.set(round(oldest, 4))
        self._m_inflight.set(len(self._inflight))
        frac = getattr(eng, "pool_free_frac", None)
        if frac is not None:
            with self._lock:  # submit()'s shed check reads it locked
                self._pool_free_frac = frac

    def _publish_burn(self) -> None:
        """Fold the rolling SLO judgment into the burn-rate gauge
        (engine thread, SLO sample cadence).  ``latest_burn`` reduces
        the capture :meth:`SLOMonitor.maybe_sample` just recorded —
        no second registry walk, no rates/percentiles computed only
        to be thrown away."""
        self._m_burn.set(self._slo.latest_burn())

    def _reject(self, reason: str) -> None:
        """Tally one shed submission (lock held by the caller)."""
        self._n_rejected[reason] = self._n_rejected.get(reason, 0) + 1
        self._m_rejected.labels(reason=reason).inc()
