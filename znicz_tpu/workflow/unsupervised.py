"""Non-backprop workflows: Kohonen SOM and RBM.

Parity with the reference's non-GD learning paths [SURVEY.md 2.2 rows
"Kohonen SOM", "RBM"; §7 "Hard parts"]: the learning rule IS the trainer
(KohonenTrainer's winner-take-all + neighborhood update; rbm_units' CD-k
updaters), so these workflows replace autodiff with the custom update
functions from :mod:`znicz_tpu.ops.kohonen` / :mod:`znicz_tpu.ops.rbm`,
while reusing the loader/decision/snapshotter machinery.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.loader.base import TRAIN, Loader
from znicz_tpu.nn.decision import Decision
from znicz_tpu.nn.train_state import TrainState
from znicz_tpu.ops import kohonen as kh, rbm as rbm_op
from znicz_tpu.workflow.snapshotter import Snapshotter
from znicz_tpu.workflow.workflow import Workflow


class _NoModel:
    """Placeholder satisfying Workflow's model attribute for custom steps."""

    params: list = []
    hyper: list = []


class KohonenWorkflow(Workflow):
    """Batch-SOM training (znicz/samples/DemoKohonen; BASELINE configs[4]).

    Metric: quantization error (mean squared distance to the winning unit)
    reported as ``loss`` so Decision/snapshot semantics carry over.
    """

    def __init__(
        self,
        loader: Loader,
        *,
        sx: int = 8,
        sy: int = 8,
        total_epochs: int = 20,
        lr0: float = 0.1,
        lr1: float = 0.01,
        sigma1: float = 1.0,
        decision: Optional[Decision] = None,
        snapshotter: Optional[Snapshotter] = None,
        parallel=None,
        prefetch_batches: int = 2,
        epoch_sync: str = "sync",
        rand_name: str = "default",
        impl: str = "auto",  # "pallas" | "xla" | "auto" (pallas on TPU)
        name: str = "KohonenWorkflow",
    ):
        super().__init__(
            loader,
            _NoModel(),
            loss_function="mse",
            target="labels",
            decision=decision
            or Decision(metric="loss", max_epochs=total_epochs),
            snapshotter=snapshotter,
            parallel=parallel,
            prefetch_batches=prefetch_batches,
            epoch_sync=epoch_sync,
            name=name,
        )
        self.sx, self.sy = sx, sy
        self.total_epochs = total_epochs
        self.lr0, self.lr1, self.sigma1 = lr0, lr1, sigma1
        self.rand_name = rand_name
        self.impl = impl
        self._n_input = int(jnp.prod(jnp.asarray(loader.sample_shape)))

    def _batch_target(self, mb):
        return np.zeros(len(mb.mask), np.int32)  # unused host-side dummy

    def _build_steps(self):
        coords = kh.grid_coords(self.sx, self.sy)
        n_steps_per_epoch = max(self.loader.n_minibatches(TRAIN), 1)
        total_steps = self.total_epochs * n_steps_per_epoch
        # fused kernel partitioning rule: under a sharded batch the kernel
        # accumulates local (num, den) partials inside shard_map and psums
        # them over the data axis — the fast path survives data parallelism
        use_pallas = self.impl == "pallas" or (
            self.impl == "auto" and jax.default_backend() in ("tpu", "axon")
        )
        pallas_mesh = (
            self.parallel.mesh
            if use_pallas and self.parallel is not None
            else None
        )
        if use_pallas:
            from znicz_tpu.ops.pallas import kohonen as pallas_kh

        def train_step(state: TrainState, x, y, mask, lr_scale):
            x = x.reshape(x.shape[0], -1)
            lr, sigma = kh.decay_schedule(
                state.step,
                total_steps,
                lr0=self.lr0,
                lr1=self.lr1,
                sigma1=self.sigma1,
                sx=self.sx,
                sy=self.sy,
            )
            if use_pallas:
                win = kh.winners(state.params, x)
                params = pallas_kh.train_step(
                    state.params,
                    x,
                    coords,
                    learning_rate=lr * lr_scale,
                    sigma=sigma,
                    mask=mask,
                    mesh=pallas_mesh,
                )
            else:
                params, win = kh.train_step(
                    state.params,
                    x,
                    coords,
                    learning_rate=lr * lr_scale,
                    sigma=sigma,
                    mask=mask,
                )
            metrics = self._qe(params, x, win, mask)
            return state._replace(params=params, step=state.step + 1), metrics

        def eval_step(params, x, y, mask):
            x = x.reshape(x.shape[0], -1)
            win = kh.winners(params, x)
            return self._qe(params, x, win, mask)

        self._finalize_steps(
            train_step, eval_step, ["loss", "n_samples", "n_err"]
        )

    @staticmethod
    def _qe(params, x, win, mask):
        d2 = jnp.sum(jnp.square(x - params["weights"][win]), axis=1)
        n = jnp.maximum(jnp.sum(mask), 1.0)
        return {
            "loss": jnp.sum(d2 * mask) / n,
            "n_samples": n,
            "n_err": jnp.zeros((), jnp.int32),
        }

    def _create_initial_state(self) -> TrainState:
        params = kh.init_params(
            self.sx, self.sy, self._n_input, rand_name=self.rand_name
        )
        return TrainState.create(params, prng.get("workflow").key())

    def weights_map(self):
        """[sy, sx, features] view of the trained map (for plotting)."""
        w = np.asarray(self.state.params["weights"])
        return w.reshape(self.sy, self.sx, -1)


class RBMWorkflow(Workflow):
    """Bernoulli RBM with CD-k (znicz/samples MNIST RBM; BASELINE configs[2]).

    Metric: masked reconstruction error as ``loss``.
    """

    def __init__(
        self,
        loader: Loader,
        *,
        n_hidden: int = 64,
        learning_rate: float = 0.1,
        cd_k: int = 1,
        max_epochs: int = 20,
        decision: Optional[Decision] = None,
        snapshotter: Optional[Snapshotter] = None,
        parallel=None,
        prefetch_batches: int = 2,
        epoch_sync: str = "sync",
        rand_name: str = "default",
        impl: str = "auto",  # "pallas" | "xla" | "auto" (pallas on TPU)
        name: str = "RBMWorkflow",
    ):
        super().__init__(
            loader,
            _NoModel(),
            loss_function="mse",
            target="labels",
            decision=decision or Decision(metric="loss", max_epochs=max_epochs),
            snapshotter=snapshotter,
            parallel=parallel,
            prefetch_batches=prefetch_batches,
            epoch_sync=epoch_sync,
            name=name,
        )
        self.n_hidden = n_hidden
        self.learning_rate = learning_rate
        self.cd_k = cd_k
        self.rand_name = rand_name
        self.impl = impl
        self._n_visible = int(jnp.prod(jnp.asarray(loader.sample_shape)))

    def _batch_target(self, mb):
        return np.zeros(len(mb.mask), np.int32)  # unused host-side dummy

    def _build_steps(self):
        from znicz_tpu.ops.pallas import rbm as pallas_rbm

        # fused CD-k kernel (hardware RNG, whole Gibbs chain in VMEM) when
        # on TPU and the problem fits the VMEM budget; the psum rule keeps
        # it available under a sharded batch (see ops/pallas/rbm.py)
        # the kernel runs per data-axis SHARD, so the VMEM check uses the
        # per-shard batch — a sharded big batch can still take the kernel
        shard_batch = self.loader.max_minibatch_size
        if self.parallel is not None:
            shard_batch = -(-shard_batch // self.parallel.n_data)
        use_pallas = self.impl == "pallas" or (
            self.impl == "auto"
            and jax.default_backend() in ("tpu", "axon")
            and pallas_rbm.fits_vmem(
                shard_batch, self._n_visible, self.n_hidden
            )
        )
        pallas_mesh = (
            self.parallel.mesh
            if use_pallas and self.parallel is not None
            else None
        )

        def train_step(state: TrainState, x, y, mask, lr_scale):
            v0 = x.reshape(x.shape[0], -1)
            if use_pallas:
                params, err = pallas_rbm.cd_step(
                    state.params,
                    v0,
                    state.step,
                    learning_rate=self.learning_rate * lr_scale,
                    cd_k=self.cd_k,
                    mask=mask,
                    mesh=pallas_mesh,
                )
            else:
                rng = jax.random.fold_in(state.key, state.step)
                params, err = rbm_op.cd_step(
                    state.params,
                    v0,
                    rng,
                    learning_rate=self.learning_rate * lr_scale,
                    cd_k=self.cd_k,
                    mask=mask,
                )
            metrics = {
                "loss": err,
                "n_samples": jnp.maximum(jnp.sum(mask), 1.0),
                "n_err": jnp.zeros((), jnp.int32),
            }
            return state._replace(params=params, step=state.step + 1), metrics

        def eval_step(params, x, y, mask):
            v0 = x.reshape(x.shape[0], -1)
            v_probs = rbm_op.visible_probs(
                params, rbm_op.hidden_probs(params, v0)
            )
            per = jnp.mean(jnp.square(v0 - v_probs), axis=1)
            n = jnp.maximum(jnp.sum(mask), 1.0)
            return {
                "loss": jnp.sum(per * mask) / n,
                "n_samples": n,
                "n_err": jnp.zeros((), jnp.int32),
            }

        self._finalize_steps(
            train_step, eval_step, ["loss", "n_samples", "n_err"]
        )

    def _create_initial_state(self) -> TrainState:
        params = rbm_op.init_params(
            self._n_visible, self.n_hidden, rand_name=self.rand_name
        )
        return TrainState.create(params, prng.get("workflow").key())
