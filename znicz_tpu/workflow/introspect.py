"""Workflow/model topology introspection.

Capability parity with the reference workflow's topology introspection and
SVG export [SURVEY.md 2.1 "Workflow engine"]: the unit DAG became a linear
layer list plus named host-side stages, so introspection is a parameter/shape
summary table plus a Graphviz DOT export of the full training topology
(loader -> layers -> evaluator -> decision/services) that any ``dot``
renderer turns into SVG.
"""

from __future__ import annotations

from typing import List

import numpy as np


def _count(params: dict) -> int:
    return int(sum(np.prod(v.shape) for v in params.values()))


def model_summary(model) -> str:
    """Human-readable per-layer table: type, param shapes, param count."""
    lines: List[str] = []
    header = f"{'#':>3}  {'layer':<22} {'params':<40} {'count':>12}"
    lines.append(header)
    lines.append("-" * len(header))
    total = 0
    for i, (kind, p) in enumerate(zip(model.layer_types, model.params)):
        shapes = ", ".join(f"{k}{list(v.shape)}" for k, v in p.items()) or "—"
        n = _count(p)
        total += n
        lines.append(f"{i:>3}  {kind:<22} {shapes:<40} {n:>12,}")
    lines.append("-" * len(header))
    lines.append(
        f"{'':>3}  {'input ' + str(list(model.input_shape)):<22} "
        f"{'output ' + str(list(model.output_shape)):<40} {total:>12,}"
    )
    return "\n".join(lines)


def to_dot(workflow) -> str:
    """Graphviz DOT of the training topology (render: ``dot -Tsvg``).

    The reference exported the unit DAG as SVG; the rebuilt topology is the
    same picture: loader feeds the jitted step (layer chain + evaluator +
    optimizer fused into one node group), whose metrics drive decision,
    snapshotter and services.
    """
    model = getattr(workflow, "model", None)
    lines = [
        "digraph workflow {",
        "  rankdir=LR;",
        '  node [shape=box, fontname="monospace"];',
        f'  loader [label="{type(workflow.loader).__name__}"];',
    ]
    prev = "loader"
    if model is not None and getattr(model, "layer_types", None):
        lines.append("  subgraph cluster_jit {")
        lines.append('    label="jit-compiled train step";')
        for i, kind in enumerate(model.layer_types):
            n = _count(model.params[i])
            label = f"{i}: {kind}" + (f"\\n{n:,} params" if n else "")
            lines.append(f'    layer{i} [label="{label}"];')
            lines.append(f"    {prev} -> layer{i};")
            prev = f"layer{i}"
        lines.append(
            f'    evaluator [label="evaluator ({workflow.loss_function})"];'
        )
        lines.append(f"    {prev} -> evaluator;")
        lines.append('    optimizer [label="grad + update"];')
        lines.append("    evaluator -> optimizer;")
        lines.append("  }")
        prev = "evaluator"
    lines.append('  decision [label="Decision"];')
    lines.append(f"  {prev} -> decision;")
    if workflow.snapshotter is not None:
        lines.append('  snapshotter [label="Snapshotter"];')
        lines.append("  decision -> snapshotter;")
    for i, service in enumerate(getattr(workflow, "services", [])):
        name = type(service).__name__
        node = f"svc_{i}_{name}"  # index: same-class services stay distinct
        lines.append(f'  {node} [label="{name}", style=dashed];')
        lines.append(f"  decision -> {node};")
    lines.append("  decision -> loader [style=dotted, label=\"next epoch\"];")
    lines.append("}")
    return "\n".join(lines)
