"""KV-cache autoregressive decoding for the transformer LM.

The reference deploys every model through export + a native forward engine
(SURVEY.md 2.4 libZnicz); the flagship LM additionally needs the other half
of its lifecycle — incremental decoding.  Re-founded TPU-first: the KV cache
is a STATIC-shape [B, T_max, H, hd] buffer per block (XLA wants fixed
shapes; validity is an index mask, not a dynamic length), each decode step
is one position through the block tower (``jax.lax.dynamic_update_slice``
into the cache, attention over the full buffer masked to ``<= pos``), and
the whole generation loop is ONE ``lax.scan`` — a single compiled program,
no per-token dispatch.

Numerics match :func:`znicz_tpu.workflow.transformer.lm_apply` exactly
(same projection/attention formulation, f32 accumulation), which the golden
tests assert position-by-position.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.ops.normalization import layer_norm
from znicz_tpu.workflow.transformer import _block_ffn


def init_kv_cache(params, batch: int, max_seq: int, *, n_heads: int):
    """Zeroed [B, T_max, H, hd] K/V buffers, one pair per block."""
    caches = []
    for block in params[1:-1]:
        inner = block["wq"].shape[1]
        head_dim = inner // n_heads
        shape = (batch, max_seq, n_heads, head_dim)
        dtype = block["wq"].dtype
        caches.append(
            {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        )
    return caches


def _block_step(
    block, x, cache, offset, *, n_heads, moe_top_k=1, moe_dispatch="dense"
):
    """One pre-LN block over ``x`` [B, Tq, D] at absolute positions
    ``offset .. offset+Tq-1``, reading/writing the KV cache.  Tq is the
    prompt length during prefill and 1 during decode — one definition for
    both, so they cannot drift from each other (and the attention math
    mirrors ``ops.attention.mha`` + ``dot_product_attention``: f32 score
    accumulation, stable softmax)."""
    b, tq, _ = x.shape
    h = layer_norm(x, block["ln1_scale"], block["ln1_bias"])

    def proj(w):
        y = jnp.dot(h, w, preferred_element_type=jnp.float32).astype(h.dtype)
        return y.reshape(b, tq, n_heads, -1)

    q, k_new, v_new = proj(block["wq"]), proj(block["wk"]), proj(block["wv"])
    k_cache = jax.lax.dynamic_update_slice(cache["k"], k_new, (0, offset, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(cache["v"], v_new, (0, offset, 0, 0))
    t_max = k_cache.shape[1]
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) * scale
    # causal validity by ABSOLUTE index: key position <= query position
    # (unwritten cache slots are > offset+Tq-1, so they mask out too)
    k_idx = jnp.arange(t_max)[None, None, None, :]
    q_idx = offset + jnp.arange(tq)[None, None, :, None]
    s = jnp.where(k_idx <= q_idx, s, -jnp.inf)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v_cache.dtype), v_cache,
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    o = o.reshape(b, tq, -1)
    x = x + jnp.dot(
        o, block["wo"], preferred_element_type=jnp.float32
    ).astype(x.dtype)
    h = layer_norm(x, block["ln2_scale"], block["ln2_bias"])
    x = x + _block_ffn(
        block, h, moe_top_k=moe_top_k, moe_dispatch=moe_dispatch
    )
    return x, {"k": k_cache, "v": v_cache}


def _embed_at(embed, tokens, offset):
    """Token + positional embedding for tokens [B, Tq] at ``offset``."""
    tq = tokens.shape[1]
    pos = jax.lax.dynamic_slice_in_dim(embed["pos"], offset, tq, axis=0)
    return embed["embed"][tokens] + pos[None, :, :]


def prefill(
    params, tokens, caches, *, n_heads, moe_top_k=1, moe_dispatch="dense"
):
    """Run the prompt [B, Tp] through the tower, filling positions
    ``0..Tp-1`` of the caches; returns (caches, last-position logits)."""
    x = _embed_at(params[0], tokens, 0)
    new_caches = []
    for block, cache in zip(params[1:-1], caches):
        x, cache = _block_step(
            block, x, cache, 0, n_heads=n_heads,
            moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
        )
        new_caches.append(cache)
    return new_caches, x[:, -1] @ params[-1]["head"]


def decode_step(
    params, caches, token, pos, *, n_heads, moe_top_k=1, moe_dispatch="dense"
):
    """One incremental step: ``token`` [B] at position ``pos`` -> (caches,
    next-position logits [B, vocab])."""
    x = _embed_at(params[0], token[:, None], pos)
    new_caches = []
    for block, cache in zip(params[1:-1], caches):
        x, cache = _block_step(
            block, x, cache, pos, n_heads=n_heads,
            moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
        )
        new_caches.append(cache)
    return new_caches, x[:, 0] @ params[-1]["head"]


def _sample(logits, key, temperature, top_k, nucleus, top_p):
    """Greedy (``greedy`` static) or temperature sampling, optionally
    truncated to the ``top_k`` highest logits and/or the ``top_p``
    nucleus (smallest prefix of the sorted distribution with cumulative
    probability >= top_p; the argmax token is always kept).  Only the
    STRUCTURAL knobs (top_k — lax.top_k wants a static k — and the
    nucleus on/off flag) are trace-time constants; ``temperature`` and
    ``top_p`` are traced operands, so sweeping them never recompiles
    the decode program."""
    logits = logits / temperature
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if nucleus:
        sl = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
        probs = jax.nn.softmax(sl, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p  # mass BEFORE the token; [..., 0] True
        thr = jnp.min(
            jnp.where(keep, sl, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits >= thr, logits, -jnp.inf)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(
    params,
    prompt: jnp.ndarray,  # [B, Tp] int32
    *,
    n_heads: int,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    rng: Optional[jax.Array] = None,
    moe_top_k: int = 1,
    moe_dispatch: str = "dense",
):
    """Autoregressive generation; returns [B, Tp + max_new_tokens] tokens
    (prompt included).  ``temperature=0`` is greedy argmax; otherwise
    softmax sampling at the given temperature (``rng`` required),
    optionally truncated to the ``top_k`` highest logits and/or the
    ``top_p`` nucleus.  The decode loop is one ``lax.scan`` — per-token
    cost is one cached block-tower step, not a growing re-forward.
    ``temperature``/``top_p`` are traced operands: sweeping them reuses
    one compiled program (only greedy<->sampling, top_k, the nucleus
    on/off flag and shapes recompile)."""
    tp = prompt.shape[1]
    t_max = tp + max_new_tokens
    max_pos = params[0]["pos"].shape[0]
    if t_max > max_pos:
        raise ValueError(
            f"prompt {tp} + max_new_tokens {max_new_tokens} exceeds the "
            f"positional table ({max_pos}); re-init the LM with a larger "
            "max_seq"
        )
    if temperature != 0.0 and rng is None:
        raise ValueError("temperature > 0 needs an rng key")
    if top_k < 0 or not 0.0 < top_p <= 1.0:
        raise ValueError(
            f"want top_k >= 0 and 0 < top_p <= 1; got {top_k}, {top_p}"
        )
    vocab = params[-1]["head"].shape[-1]
    if top_k >= vocab:
        top_k = 0  # full support — no truncation (mirrors moe's clamp)
    if rng is None:
        # only reachable in greedy mode (temperature != 0 raised above),
        # where the key is NEVER consumed — the scan just wants a
        # key-typed operand.  A registry draw here would advance (and
        # snapshot) a stream nothing reads; a fixed dummy is the honest
        # spelling, same pattern as ops/pallas/rbm.py.
        rng = jax.random.key(0)  # znicz-check: disable=ZNC004
    return _generate_impl(
        params,
        jnp.asarray(prompt, jnp.int32),
        jnp.float32(temperature),
        jnp.float32(top_p),
        rng,
        n_heads=n_heads,
        max_new_tokens=max_new_tokens,
        greedy=temperature == 0.0,
        top_k=top_k,
        nucleus=top_p < 1.0,
        moe_top_k=moe_top_k,
        moe_dispatch=moe_dispatch,
    )


@partial(
    jax.jit,
    static_argnames=(
        "n_heads", "max_new_tokens", "greedy", "top_k", "nucleus",
        "moe_top_k", "moe_dispatch",
    ),
)
def _generate_impl(
    params, prompt, temperature, top_p, rng, *, n_heads, max_new_tokens,
    greedy, top_k, nucleus, moe_top_k, moe_dispatch,
):
    b, tp = prompt.shape
    t_max = tp + max_new_tokens

    def sample(logits, key):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return _sample(logits, key, temperature, top_k, nucleus, top_p)

    caches = init_kv_cache(params, b, t_max, n_heads=n_heads)
    caches, logits = prefill(
        params, prompt, caches, n_heads=n_heads,
        moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
    )
    keys = jax.random.split(rng, max_new_tokens)
    first = sample(logits, keys[0])

    def step(carry, key):
        caches, token, pos = carry
        caches, logits = decode_step(
            params, caches, token, pos, n_heads=n_heads,
            moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
        )
        nxt = sample(logits, key)
        return (caches, nxt, pos + 1), nxt

    (_, _, _), rest = jax.lax.scan(
        step, (caches, first, jnp.asarray(tp)), keys[1:]
    )
    out = jnp.concatenate(
        [prompt, first[:, None], rest.T.astype(jnp.int32)], axis=1
    )
    return out[:, : t_max]
