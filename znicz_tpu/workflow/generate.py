"""KV-cache autoregressive decoding for the transformer LM.

The reference deploys every model through export + a native forward engine
(SURVEY.md 2.4 libZnicz); the flagship LM additionally needs the other half
of its lifecycle — incremental decoding.  Re-founded TPU-first: the KV cache
is a STATIC-shape [B, T_max, H, hd] buffer per block (XLA wants fixed
shapes; validity is an index mask, not a dynamic length), each decode step
is one position through the block tower (``jax.lax.dynamic_update_slice``
into the cache, attention over the full buffer masked to ``<= pos``), and
the whole generation loop is ONE ``lax.while_loop`` — a single compiled
program, no per-token dispatch, that exits as soon as every row has hit
the EOS id (or the budget).

Serving fast path (docs/SERVING.md): prompts are LEFT-padded to a small
geometric ladder of length buckets and budgets round up a rung, so any
request stream hits a handful of compiled programs instead of one per
shape.  Padding is numerically inert — per-row ``start`` offsets mask the
pad slots out of attention and shift positional embeddings, which the
golden tests assert against the unpadded reference position-by-position.
:func:`generate_serve` fronts this with an explicit executable cache
keyed on ``(bucket_tp, bucket_new, B, sampling-structure)`` and a
compile-count introspection hook (:func:`serve_cache_stats`).

Numerics match :func:`znicz_tpu.workflow.transformer.lm_apply` exactly
(same projection/attention formulation, f32 accumulation), which the golden
tests assert position-by-position.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu import observability
from znicz_tpu.observability import device as device_telemetry
from znicz_tpu.ops.attention import paged_attention
from znicz_tpu.ops.normalization import layer_norm
from znicz_tpu.workflow.transformer import _block_ffn


def init_kv_cache(params, batch: int, max_seq: int, *, n_heads: int):
    """Zeroed [B, T_max, H, hd] K/V buffers, one pair per block."""
    caches = []
    for block in params[1:-1]:
        inner = block["wq"].shape[1]
        head_dim = inner // n_heads
        shape = (batch, max_seq, n_heads, head_dim)
        dtype = block["wq"].dtype
        caches.append(
            {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        )
    return caches


def _block_step(
    block, x, cache, offset, *, n_heads, start=None, moe_top_k=1,
    moe_dispatch="dense",
):
    """One pre-LN block over ``x`` [B, Tq, D] at absolute positions
    ``offset .. offset+Tq-1``, reading/writing the KV cache.  Tq is the
    prompt length during prefill and 1 during decode — one definition for
    both, so they cannot drift from each other (and the attention math
    mirrors ``ops.attention.mha`` + ``dot_product_attention``: f32 score
    accumulation, stable softmax).  ``start`` [B] marks each row's first
    real (non-pad) position under left-padding; keys before it are masked
    out of attention."""
    b, tq, _ = x.shape
    h = layer_norm(x, block["ln1_scale"], block["ln1_bias"])

    def proj(w):
        y = jnp.dot(h, w, preferred_element_type=jnp.float32).astype(h.dtype)
        return y.reshape(b, tq, n_heads, -1)

    q, k_new, v_new = proj(block["wq"]), proj(block["wk"]), proj(block["wv"])
    k_cache = jax.lax.dynamic_update_slice(cache["k"], k_new, (0, offset, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(cache["v"], v_new, (0, offset, 0, 0))
    t_max = k_cache.shape[1]
    # np.sqrt of a STATIC shape is a trace-time constant, not a host
    # effect (the project-wide pass sees this helper as traced)
    scale = 1.0 / np.sqrt(q.shape[-1])  # znicz-check: disable=ZNC002
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) * scale
    # causal validity by ABSOLUTE index: key position <= query position
    # (unwritten cache slots are > offset+Tq-1, so they mask out too)
    k_idx = jnp.arange(t_max)[None, None, None, :]
    q_idx = offset + jnp.arange(tq)[None, None, :, None]
    valid = k_idx <= q_idx
    if start is not None:
        # left-padding: keys before the row's first real token are inert.
        # A pad-region query (q < start) keeps exactly its own position so
        # its softmax stays finite (all--inf rows would breed NaNs that
        # 0*NaN-poison real rows through the value einsum); its output is
        # discarded and its k/v never enter a real query's window.
        st = start[:, None, None, None]
        valid = valid & (k_idx >= jnp.minimum(st, q_idx))
    s = jnp.where(valid, s, -jnp.inf)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v_cache.dtype), v_cache,
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    o = o.reshape(b, tq, -1)
    x = x + jnp.dot(
        o, block["wo"], preferred_element_type=jnp.float32
    ).astype(x.dtype)
    h = layer_norm(x, block["ln2_scale"], block["ln2_bias"])
    x = x + _block_ffn(
        block, h, moe_top_k=moe_top_k, moe_dispatch=moe_dispatch
    )
    return x, {"k": k_cache, "v": v_cache}


def _embed_at(embed, tokens, offset, start=None):
    """Token + positional embedding for tokens [B, Tq] at ``offset``.

    With ``start`` [B] (left-padding), each row's positional index is
    RELATIVE to its first real token (absolute - start), so a padded row
    sees exactly the position ids the unpadded prompt would — positional
    parity is what makes left-padding numerically inert."""
    tq = tokens.shape[1]
    if start is None:
        pos = jax.lax.dynamic_slice_in_dim(embed["pos"], offset, tq, axis=0)
        return embed["embed"][tokens] + pos[None, :, :]
    rel = offset + jnp.arange(tq)[None, :] - start[:, None]
    rel = jnp.clip(rel, 0, embed["pos"].shape[0] - 1)
    return embed["embed"][tokens] + embed["pos"][rel]


def prefill(
    params, tokens, caches, *, n_heads, start=None, moe_top_k=1,
    moe_dispatch="dense",
):
    """Run the prompt [B, Tp] through the tower, filling positions
    ``0..Tp-1`` of the caches; returns (caches, last-position logits).
    ``start`` [B]: first real position per row of a LEFT-padded prompt
    (the last position is always real, so the returned logits are too)."""
    x = _embed_at(params[0], tokens, 0, start)
    new_caches = []
    for block, cache in zip(params[1:-1], caches):
        x, cache = _block_step(
            block, x, cache, 0, n_heads=n_heads, start=start,
            moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
        )
        new_caches.append(cache)
    return new_caches, x[:, -1] @ params[-1]["head"]


def decode_step(
    params, caches, token, pos, *, n_heads, start=None, moe_top_k=1,
    moe_dispatch="dense",
):
    """One incremental step: ``token`` [B] at position ``pos`` -> (caches,
    next-position logits [B, vocab])."""
    x = _embed_at(params[0], token[:, None], pos, start)
    new_caches = []
    for block, cache in zip(params[1:-1], caches):
        x, cache = _block_step(
            block, x, cache, pos, n_heads=n_heads, start=start,
            moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
        )
        new_caches.append(cache)
    return new_caches, x[:, 0] @ params[-1]["head"]


# ---------------------------------------------------------------------------
# Paged KV cache (vLLM/PagedAttention lineage, docs/SERVING.md): K/V live
# in a shared [n_blocks, block_size, H, hd] pool per layer and each row
# owns an ordered block table — block-granular allocation instead of a
# dense [B, T_max] reservation per slot, so memory scales with the tokens
# actually decoded and the pool's free blocks ARE the concurrency budget.

NULL_BLOCK = 0  # reserved pool block: write target for idle/done rows


def init_paged_kv(params, n_blocks: int, block_size: int, *, n_heads: int):
    """Zeroed ``[n_blocks, block_size, H, hd]`` K/V pools, one pair per
    block of the tower.  Pool block ``NULL_BLOCK`` (index 0) is reserved
    as the null write target — allocators must hand out ``1..n_blocks-1``
    — so rows with nothing to say (done, idle slot) can always write
    somewhere harmless instead of branching."""
    if n_blocks < 2 or block_size < 1:
        raise ValueError(
            f"want n_blocks >= 2 (one is the reserved null block) and "
            f"block_size >= 1; got {n_blocks}, {block_size}"
        )
    pools = []
    for block in params[1:-1]:
        inner = block["wq"].shape[1]
        head_dim = inner // n_heads
        shape = (n_blocks, block_size, n_heads, head_dim)
        dtype = block["wq"].dtype
        pools.append(
            {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        )
    return pools


def _paged_block_step(
    block, x, pool, write, tables, q_pos, *, n_heads, block_size,
    start=None, moe_top_k=1, moe_dispatch="dense",
):
    """One pre-LN block over ``x`` [B, Tq, D] with paged KV: ``write``
    scatters this layer's new K/V into the pool (the caller resolves
    block ids once — the same indices serve every layer) and attention
    gathers through the block table (:func:`ops.attention.paged_attention`
    — same masked stable-softmax numerics as the dense
    :func:`_block_step`, asserted by the paged goldens)."""
    b, tq, _ = x.shape
    h = layer_norm(x, block["ln1_scale"], block["ln1_bias"])

    def proj(w):
        y = jnp.dot(h, w, preferred_element_type=jnp.float32).astype(h.dtype)
        return y.reshape(b, tq, n_heads, -1)

    q, k_new, v_new = proj(block["wq"]), proj(block["wk"]), proj(block["wv"])
    k_pool = write(pool["k"], k_new)
    v_pool = write(pool["v"], v_new)
    o = paged_attention(
        q, k_pool, v_pool, tables, q_pos, block_size=block_size,
        start=start,
    )
    o = o.reshape(b, tq, -1)
    x = x + jnp.dot(
        o, block["wo"], preferred_element_type=jnp.float32
    ).astype(x.dtype)
    h = layer_norm(x, block["ln2_scale"], block["ln2_bias"])
    x = x + _block_ffn(
        block, h, moe_top_k=moe_top_k, moe_dispatch=moe_dispatch
    )
    return x, {"k": k_pool, "v": v_pool}


def paged_prefill_chunk(
    params, pools, table, tokens, offset, *, n_heads, block_size,
    start=None, last=None, moe_top_k=1, moe_dispatch="dense",
):
    """Process ONE aligned chunk of a single prompt through the tower,
    writing its K/V into the row's blocks; returns ``(pools, logits)``
    at the chunk's ``last`` position (its final position by default).

    ``tokens`` is ``[1, C]`` with ``C == block_size`` and ``offset`` a
    multiple of ``block_size`` — the chunk occupies exactly one block,
    so the write is one whole-block scatter and the compiled program has
    a SINGLE shape regardless of prompt length (chunked prefill's whole
    point: a long prompt is N invocations of this one program,
    interleavable with decode chunks, instead of one monolithic
    per-bucket prefill that stalls the batch).  ``table`` is the row's
    [M] block table.

    Prompts anchor at position 0 and the FINAL chunk is RIGHT-padded to
    the block boundary (prefix-cache alignment: a shared prefix fills
    identical block contents whatever the full prompt's length — a left
    pad would shift every block by ``-len % block_size`` and kill
    sharing).  ``last`` (traced) is the in-chunk index of the prompt's
    last real token, so the returned logits are the first-token logits
    even when the tail of the chunk is pad.  The pad positions DO write
    (garbage) K/V at absolute positions past the prompt, but validity
    is by absolute index — no query ever attends a position it hasn't
    reached — and incremental decode overwrites each pad slot before
    its position becomes visible.  ``start`` [1] is retained for
    left-padded callers (legacy tests); the engine passes zeros."""
    c = tokens.shape[1]
    if c != block_size:
        raise ValueError(
            f"chunk length {c} must equal block_size {block_size} "
            "(one chunk == one block)"
        )
    blk = table[offset // block_size]
    x = _embed_at(params[0], tokens, offset, start)
    q_pos = offset + jnp.arange(c)[None, :]

    def write(pool, new):
        return pool.at[blk].set(new[0])

    new_pools = []
    for block, pool in zip(params[1:-1], pools):
        x, pool = _paged_block_step(
            block, x, pool, write, table[None], q_pos, n_heads=n_heads,
            block_size=block_size, start=start, moe_top_k=moe_top_k,
            moe_dispatch=moe_dispatch,
        )
        new_pools.append(pool)
    if last is None:
        xl = x[:, -1]
    else:
        xl = jax.lax.dynamic_index_in_dim(x, last, axis=1, keepdims=False)
    return new_pools, xl @ params[-1]["head"]


def copy_paged_block(pools, src, dst):
    """Copy pool block ``src`` into ``dst`` across every layer's K/V
    pool — the copy-on-write split for paged prefix sharing: when a row
    must write into a block other tables (or the prefix cache) still
    reference, the engine allocates a fresh block, copies the shared
    content here, and retargets only its own table entry.  ``src`` and
    ``dst`` are traced operands, so one compiled program serves every
    split."""
    new_pools = []
    for pool in pools:
        new_pools.append(
            {
                "k": pool["k"].at[dst].set(pool["k"][src]),
                "v": pool["v"].at[dst].set(pool["v"][src]),
            }
        )
    return new_pools


def paged_decode_step(
    params, pools, tables, token, pos, *, n_heads, block_size,
    start=None, write_mask=None, moe_top_k=1, moe_dispatch="dense",
):
    """One incremental paged step: ``token`` [B] at PER-ROW positions
    ``pos`` [B] -> ``(pools, next logits [B, vocab])``.

    Each row writes its new K/V at ``(tables[b, pos_b // bs],
    pos_b % bs)`` — rows own disjoint blocks, so the batched scatter
    never collides — and attends through its own table.  Rows with
    ``write_mask`` False (done/idle slots) write to the reserved
    ``NULL_BLOCK`` instead, so a retired-but-still-carried row can
    never scribble into a block the allocator has handed to someone
    else.  Per-row positions are native here (no vmap-into-scatter as
    in the dense engine chunk): the block table IS the indirection."""
    b = token.shape[0]
    rows = jnp.arange(b)
    blk = tables[rows, pos // block_size]
    if write_mask is not None:
        blk = jnp.where(write_mask, blk, NULL_BLOCK)
    slot = pos % block_size
    x = _embed_rows(params[0], token, pos, start)

    def write(pool, new):
        return pool.at[blk, slot].set(new[:, 0])

    new_pools = []
    for block, pool in zip(params[1:-1], pools):
        x, pool = _paged_block_step(
            block, x, pool, write, tables, pos[:, None], n_heads=n_heads,
            block_size=block_size, start=start, moe_top_k=moe_top_k,
            moe_dispatch=moe_dispatch,
        )
        new_pools.append(pool)
    return new_pools, x[:, 0] @ params[-1]["head"]


def _embed_rows(embed, token, pos, start=None):
    """Token + positional embedding at PER-ROW absolute positions (the
    paged twin of :func:`_embed_at`, which takes one shared offset).
    ``token``/``pos`` are ``[B]`` (one decode step) or ``[B, W]`` (a
    speculative verify chunk — W consecutive positions per row).  With
    ``start`` the position index is row-relative, same left-padding
    contract."""
    if token.ndim == 1:
        token = token[:, None]
        pos = pos[:, None]
    rel = pos if start is None else pos - start[:, None]
    rel = jnp.clip(rel, 0, embed["pos"].shape[0] - 1)
    return embed["embed"][token] + embed["pos"][rel]


def paged_verify_chunk(
    params, pools, tables, tokens, pos, *, n_heads, block_size,
    start=None, write_mask=None, moe_top_k=1, moe_dispatch="dense",
):
    """Score W tokens per row at per-row positions ``pos .. pos+W-1``
    through the paged tower in ONE forward pass — the speculative-
    decoding VERIFY primitive; returns ``(pools, logits [B, W, vocab])``
    where ``logits[:, i]`` is the next-token distribution AFTER input
    token ``i``.

    ``tokens`` is ``[B, W]``: each row's current last sampled token
    followed by its drafted continuation (padded past the draft).  Each
    position writes its K/V at ``(tables[b, (pos_b+i)//bs],
    (pos_b+i)%bs)`` before attention gathers through the table, so a
    query at position ``pos_b+i`` attends exactly what ``i`` sequential
    :func:`paged_decode_step` calls would have seen — same masked
    stable-softmax numerics, same validity-by-absolute-index contract,
    which is what makes greedy speculative decode token-identical to
    non-speculative decode.  ``write_mask`` ``[B, W]`` routes masked
    positions (done rows, positions past the row's budget — whose
    table lookup may even fall off the windowed table) to the reserved
    ``NULL_BLOCK``.  Rejected positions DO leave garbage K/V behind;
    that is safe for the same reason prefill's right-pad is: validity
    is by absolute index, and the next step's writes overwrite every
    garbage position before any query can reach it — the engine
    additionally truncates the block table back to the accepted prefix
    (rollback is bookkeeping, not copies).  W, like the chunk length in
    :func:`paged_prefill_chunk`, is a compile-time shape: the engine
    snaps it to a small bucket ladder so accepted/drafted lengths are
    traced operands and no accepted length ever compiles a new
    program."""
    b, w = tokens.shape
    rows = jnp.arange(b)[:, None]
    pos_w = pos[:, None] + jnp.arange(w)[None, :]  # [B, W]
    blk = tables[rows, pos_w // block_size]
    if write_mask is not None:
        blk = jnp.where(write_mask, blk, NULL_BLOCK)
    slot = pos_w % block_size
    x = _embed_rows(params[0], tokens, pos_w, start)

    def write(pool, new):
        return pool.at[blk, slot].set(new)

    new_pools = []
    for block, pool in zip(params[1:-1], pools):
        x, pool = _paged_block_step(
            block, x, pool, write, tables, pos_w, n_heads=n_heads,
            block_size=block_size, start=start, moe_top_k=moe_top_k,
            moe_dispatch=moe_dispatch,
        )
        new_pools.append(pool)
    return new_pools, x @ params[-1]["head"]


# ---------------------------------------------------------------------------
# Speculative drafting (Leviathan et al. 2023 lineage).  The drafter is
# a tiny HOST-side interface — ``propose(context, k) -> up to k token
# ids`` — so the paged engine's verify path is agnostic to where the
# guesses come from: prompt-lookup below costs zero extra weights; a
# draft-model drafter (a small transformer_lm sharing the target's
# tokenizer) plugs into the same hook.

# verify-width (k+1) bucket ladder: drafted lengths snap UP a rung so
# the verify program compiles once per rung, never per accepted length
DEFAULT_SPEC_BUCKETS = (2, 4, 8)


class PromptLookupDrafter:
    """Prompt-lookup / n-gram drafting (Saxena 2023): propose the
    continuation of the MOST RECENT earlier occurrence of the context's
    final n-gram, longest n first.  The context is the row's own prompt
    plus everything it has emitted — repetitive prompts (retrieval,
    code, multi-turn chat) and self-repeating generations both draft
    well, and the proposal costs a few numpy comparisons, no weights.

    Duck-typed drafter contract (what :class:`~znicz_tpu.services
    .engine.PagedDecodeEngine` calls every speculative tick, per
    decoding row): ``propose(context, k)`` takes the 1-D int32 token
    context and returns UP TO ``k`` proposed next tokens (empty when it
    has no confident guess — the engine then falls back to the plain
    decode chunk, so an unpredictable stream never pays verify
    overhead).  ``ngram_min=2`` by default: a 1-gram match is noise on
    most streams, and a wasted verify pass costs real tower compute
    where an abstained tick costs nothing."""

    def __init__(self, ngram_max: int = 3, ngram_min: int = 2):
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError(
                f"want 1 <= ngram_min <= ngram_max; got "
                f"{ngram_min}, {ngram_max}"
            )
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)

    def propose(self, context, k: int) -> np.ndarray:
        ctx = np.asarray(context, np.int32).reshape(-1)
        if k <= 0:
            return np.zeros((0,), np.int32)
        for n in range(self.ngram_max, self.ngram_min - 1, -1):
            if ctx.size <= n:
                continue
            pattern = ctx[-n:]
            win = np.lib.stride_tricks.sliding_window_view(ctx, n)
            hits = np.nonzero((win == pattern).all(axis=1))[0]
            # need at least one continuation token; this also drops the
            # terminal self-match (the pattern matching itself)
            hits = hits[hits + n < ctx.size]
            if hits.size:
                # prefer the LATEST occurrence with k continuation
                # tokens available: inside a repeated run the most
                # recent match sits one step from the end and could
                # only ever propose a single token, while an earlier
                # occurrence of the same pattern carries the whole
                # periodic continuation (the continuation may overlap
                # the context tail — that IS the periodic guess)
                full = hits[hits + n + int(k) <= ctx.size]
                i = int(full[-1] if full.size else hits[-1])
                return ctx[i + n: i + n + int(k)].copy()
        return np.zeros((0,), np.int32)


def _filter_logits(logits, temperature, top_k, nucleus, top_p):
    """The sampling truncation pipeline: temperature scaling, optional
    ``top_k`` cut (lax.top_k wants a static k) and optional ``top_p``
    nucleus (smallest prefix of the sorted distribution with cumulative
    probability >= top_p; the argmax token is always kept).  Operates
    on the LAST axis, so it serves ``[B, vocab]`` decode logits and
    ``[B, W, vocab]`` speculative verify logits alike — the ONE owner
    of the truncation semantics, shared by :func:`_sample` and the
    verify program's rejection sampler (the accept probability must be
    computed on exactly the distribution :func:`_sample` draws from)."""
    logits = logits / temperature
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if nucleus:
        sl = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
        probs = jax.nn.softmax(sl, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p  # mass BEFORE the token; [..., 0] True
        thr = jnp.min(
            jnp.where(keep, sl, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits >= thr, logits, -jnp.inf)
    return logits


def _sample(logits, key, temperature, top_k, nucleus, top_p):
    """Greedy (``greedy`` static) or temperature sampling over the
    truncated distribution (:func:`_filter_logits`).  Only the
    STRUCTURAL knobs (top_k and the nucleus on/off flag) are trace-time
    constants; ``temperature`` and ``top_p`` are traced operands, so
    sweeping them never recompiles the decode program."""
    return jax.random.categorical(
        key, _filter_logits(logits, temperature, top_k, nucleus, top_p),
        axis=-1,
    ).astype(jnp.int32)


def _check_sampling_args(params, temperature, top_k, top_p, rng, eos_id):
    """Shared argument validation for generate()/generate_serve()/the
    engine; returns (top_k, rng) with the full-support clamp and greedy
    dummy key applied."""
    if temperature != 0.0 and rng is None:
        raise ValueError("temperature > 0 needs an rng key")
    if top_k < 0 or not 0.0 < top_p <= 1.0:
        raise ValueError(
            f"want top_k >= 0 and 0 < top_p <= 1; got {top_k}, {top_p}"
        )
    vocab = params[-1]["head"].shape[-1]
    if eos_id is not None and not 0 <= eos_id < vocab:
        raise ValueError(f"eos_id {eos_id} outside vocab {vocab}")
    if top_k >= vocab:
        top_k = 0  # full support — no truncation (mirrors moe's clamp)
    if rng is None:
        # only reachable in greedy mode (temperature != 0 raised above),
        # where the key is NEVER consumed — the loop just wants a
        # key-typed operand.  A registry draw here would advance (and
        # snapshot) a stream nothing reads; a fixed dummy is the honest
        # spelling, same pattern as ops/pallas/rbm.py.
        rng = jax.random.key(0)  # znicz-check: disable=ZNC004
    return top_k, rng


def generate(
    params,
    prompt: jnp.ndarray,  # [B, Tp] int32
    *,
    n_heads: int,
    max_new_tokens: int,
    eos_id: Optional[int] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    rng: Optional[jax.Array] = None,
    moe_top_k: int = 1,
    moe_dispatch: str = "dense",
):
    """Autoregressive generation; returns [B, Tp + max_new_tokens] tokens
    (prompt included).  ``temperature=0`` is greedy argmax; otherwise
    softmax sampling at the given temperature (``rng`` required),
    optionally truncated to the ``top_k`` highest logits and/or the
    ``top_p`` nucleus.  The decode loop is one ``lax.while_loop`` —
    per-token cost is one cached block-tower step, not a growing
    re-forward, and with ``eos_id`` set the loop EXITS as soon as every
    row has emitted EOS (rows that finish early emit ``eos_id`` for the
    rest of the budget, identical to the full-budget run up to EOS).
    ``temperature``/``top_p`` are traced operands: sweeping them reuses
    one compiled program (only greedy<->sampling, top_k, the nucleus
    on/off flag, ``eos_id`` and shapes recompile)."""
    if max_new_tokens < 1:
        raise ValueError(f"want max_new_tokens >= 1; got {max_new_tokens}")
    tp = prompt.shape[1]
    t_max = tp + max_new_tokens
    max_pos = params[0]["pos"].shape[0]
    if t_max > max_pos:
        raise ValueError(
            f"prompt {tp} + max_new_tokens {max_new_tokens} exceeds the "
            f"positional table ({max_pos}); re-init the LM with a larger "
            "max_seq"
        )
    top_k, rng = _check_sampling_args(
        params, temperature, top_k, top_p, rng, eos_id
    )
    return _generate_impl(
        params,
        jnp.asarray(prompt, jnp.int32),
        None,
        jnp.int32(max_new_tokens),
        jnp.float32(temperature),
        jnp.float32(top_p),
        rng,
        n_heads=n_heads,
        max_new_tokens=max_new_tokens,
        greedy=temperature == 0.0,
        top_k=top_k,
        nucleus=top_p < 1.0,
        eos_id=eos_id,
        moe_top_k=moe_top_k,
        moe_dispatch=moe_dispatch,
    )


@partial(
    jax.jit,
    static_argnames=(
        "n_heads", "max_new_tokens", "greedy", "top_k", "nucleus",
        "eos_id", "moe_top_k", "moe_dispatch",
    ),
)
def _generate_impl(
    params, prompt, start, budget, temperature, top_p, rng, *, n_heads,
    max_new_tokens, greedy, top_k, nucleus, eos_id, moe_top_k,
    moe_dispatch,
):
    """One compiled decode program: prefill + a while_loop over decode
    steps carrying a per-row done-mask.  ``start`` is None for unpadded
    prompts (None is an empty pytree, so the lean no-mask program
    compiles) or [B] first-real-position offsets for left-padded ones.
    ``budget`` is the REQUESTED token count as a traced operand:
    ``max_new_tokens`` (the budget-ladder rung) sizes the buffers, but
    the loop stops at ``budget`` — rounding a request up a rung costs
    compiled shapes, never decode steps.  Per-step sampling keys are
    ``fold_in(rng, step)`` — derivable at any step index without
    materializing a presplit key array in the carry."""
    b, tp = prompt.shape
    t_max = tp + max_new_tokens
    budget = jnp.minimum(budget, max_new_tokens)  # out-buffer bound

    def sample(logits, i):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return _sample(
            logits, jax.random.fold_in(rng, i), temperature, top_k,
            nucleus, top_p,
        )

    caches = init_kv_cache(params, b, t_max, n_heads=n_heads)
    caches, logits = prefill(
        params, prompt, caches, n_heads=n_heads, start=start,
        moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
    )
    first = sample(logits, 0)
    fill = jnp.int32(eos_id if eos_id is not None else 0)
    out = jnp.full((b, max_new_tokens), fill, jnp.int32)
    out = jax.lax.dynamic_update_slice(out, first[:, None], (0, 0))
    if eos_id is not None:
        done = first == eos_id
    else:
        done = jnp.zeros((b,), bool)

    def cond(carry):
        _, _, i, done, _ = carry
        return (i < budget) & ~jnp.all(done)

    def body(carry):
        caches, token, i, done, out = carry
        caches, logits = decode_step(
            params, caches, token, tp + i - 1, n_heads=n_heads,
            start=start, moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
        )
        nxt = sample(logits, i)
        if eos_id is not None:
            nxt = jnp.where(done, fill, nxt)
            done = done | (nxt == eos_id)
        out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, i))
        return (caches, nxt, i + 1, done, out)

    _, _, _, _, out = jax.lax.while_loop(
        cond, body, (caches, first, jnp.int32(1), done, out)
    )
    return jnp.concatenate([prompt, out], axis=1)


# ---------------------------------------------------------------------------
# Serving fast path: shape buckets + an explicit executable cache.

# Geometric x2 ladders: a request stream of arbitrary prompt lengths /
# token budgets compiles at most len(ladder) programs per sampling
# structure instead of one per distinct shape.
DEFAULT_PROMPT_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048)
DEFAULT_BUDGET_LADDER = (16, 32, 64, 128, 256, 512, 1024)


def bucket_for(n: int, ladder: Sequence[int]) -> int:
    """Smallest rung >= ``n``; past the top rung keep doubling it, so the
    ladder stays geometric and the compiled-program count logarithmic in
    the largest request ever seen."""
    if n <= 0:
        raise ValueError(f"want a positive length; got {n}")
    for rung in ladder:
        if n <= rung:
            return int(rung)
    rung = int(ladder[-1])
    while rung < n:
        rung *= 2
    return rung


def pack_prompts(prompts, bucket: int, pad_id: int):
    """LEFT-pad ragged prompts into one [B, bucket] int32 batch.

    Returns ``(tokens, start)`` where ``start[b]`` is the index of row
    b's first real token — the attention mask and positional embeddings
    consume it to make the padding numerically inert (left-padding keeps
    every row's LAST position real, so prefill logits need no gather)."""
    tokens = np.full((len(prompts), bucket), pad_id, np.int32)
    start = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        p = np.asarray(p, np.int32).reshape(-1)
        if p.size == 0:
            raise ValueError(f"prompt {i} is empty")
        if p.size > bucket:
            raise ValueError(
                f"prompt {i} length {p.size} exceeds bucket {bucket}"
            )
        tokens[i, bucket - p.size:] = p
        start[i] = bucket - p.size
    return jnp.asarray(tokens), jnp.asarray(start)


def _params_fingerprint(params):
    """Hashable (treedef, shapes/dtypes) key component: one executable
    serves one parameter GEOMETRY (values may change, e.g. after more
    training — shapes may not)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    return treedef, tuple(
        (tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves
    )


class _ServeCache:
    """Explicit executable cache for the serving decode path.

    ``jax.jit`` already memoizes by (shapes, statics); this layer makes
    the serving contract INSPECTABLE: every distinct key is one real
    AOT-compiled executable (``lower().compile()``), so ``programs`` is
    an exact compile count, not an inference from timing.  The
    request/hit/compile tallies live in the process-wide metrics
    registry (``znicz_serve_cache_*_total`` — visible on ``/metrics``
    and in ``status.json``); the attributes here are read-through
    views, not a second ledger."""

    def __init__(self):
        self.programs = {}  # key -> compiled executable
        self._requests = observability.counter(
            "znicz_serve_cache_requests_total",
            "generate_serve() invocations",
        )
        self._hits = observability.counter(
            "znicz_serve_cache_hits_total",
            "generate_serve() calls served without compiling",
        )
        self._compiles = observability.counter(
            "znicz_serve_cache_compiles_total",
            "generate_serve() AOT compiles (distinct executable keys)",
        )

    @property
    def hits(self) -> int:
        return int(self._hits.value)

    @property
    def requests(self) -> int:
        return int(self._requests.value)

    @property
    def compiles(self) -> int:
        return int(self._compiles.value)

    def record_request(self) -> None:
        self._requests.inc()

    def record_hit(self) -> None:
        self._hits.inc()

    def record_compile(self) -> None:
        self._compiles.inc()

    def reset(self):
        self.programs.clear()
        self._requests.reset()
        self._hits.reset()
        self._compiles.reset()


_serve_cache = _ServeCache()


def serve_cache_stats() -> dict:
    """Compile-count introspection hook for the serving path: one entry
    in ``programs`` per (bucket_tp, bucket_new, B, sampling-structure)
    ever compiled; ``hits`` counts requests served without compiling."""
    return {
        "programs": len(_serve_cache.programs),
        "hits": _serve_cache.hits,
        "requests": _serve_cache.requests,
        "compiles": _serve_cache.compiles,
        "keys": sorted(
            str(k[:-1]) for k in _serve_cache.programs
        ),  # drop the params fingerprint — noise for humans
        "jit_entries": _generate_impl._cache_size(),
    }


def reset_serve_cache() -> None:
    """Drop all cached serving executables and zero the counters."""
    _serve_cache.reset()


def generate_serve(
    params,
    prompt,  # [B, Tp] int32 (rectangular; ragged streams -> engine.py)
    *,
    n_heads: int,
    max_new_tokens: int,
    eos_id: Optional[int] = None,
    pad_id: Optional[int] = None,
    prompt_buckets: Sequence[int] = DEFAULT_PROMPT_BUCKETS,
    budget_ladder: Sequence[int] = DEFAULT_BUDGET_LADDER,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    rng: Optional[jax.Array] = None,
    moe_top_k: int = 1,
    moe_dispatch: str = "dense",
):
    """Shape-bucketed serving twin of :func:`generate`.

    Left-pads the prompt to the next prompt-length bucket and rounds the
    token budget up a ladder rung, so any request stream hits a handful
    of compiled programs; the executable is fetched from (or AOT-compiled
    into) the explicit :data:`_serve_cache` keyed on
    ``(bucket_tp, bucket_new, B, sampling-structure)``.  Returns
    [B, Tp + max_new_tokens] tokens exactly like ``generate()`` — padding
    stripped, budget trimmed back to the request — and matches it
    token-for-token up to EOS (golden-tested)."""
    if max_new_tokens < 1:
        raise ValueError(f"want max_new_tokens >= 1; got {max_new_tokens}")
    prompt = jnp.asarray(prompt, jnp.int32)
    b, tp = prompt.shape
    max_pos = params[0]["pos"].shape[0]
    if tp + max_new_tokens > max_pos:
        raise ValueError(
            f"prompt {tp} + max_new_tokens {max_new_tokens} exceeds the "
            f"positional table ({max_pos}); re-init the LM with a larger "
            "max_seq"
        )
    bucket_tp = bucket_for(tp, prompt_buckets)
    bucket_new = bucket_for(max_new_tokens, budget_ladder)
    if bucket_tp + bucket_new > max_pos:
        # rounding up must never reject a feasible request: shrink the
        # budget rung into the table, then fall back to exact shapes
        # (a rare capacity-edge compile beats a refused request)
        bucket_new = max_pos - bucket_tp
        if bucket_new < max_new_tokens:
            bucket_tp, bucket_new = tp, max_new_tokens
    top_k, rng = _check_sampling_args(
        params, temperature, top_k, top_p, rng, eos_id
    )
    if pad_id is None:
        pad_id = eos_id if eos_id is not None else 0
    pad = bucket_tp - tp
    if pad:
        padded = jnp.concatenate(
            [jnp.full((b, pad), pad_id, jnp.int32), prompt], axis=1
        )
    else:
        padded = prompt
    # always pass start (even all-zeros at exact bucket size) so ONE
    # program per bucket serves every prompt length inside it
    start = jnp.full((b,), pad, jnp.int32)
    greedy = temperature == 0.0
    nucleus = top_p < 1.0
    # n_heads is in the key although it rarely differs between equal
    # param geometries: head splits of the same [D, D] projections
    # compile DIFFERENT programs, and a shared-shape cache hit across
    # head counts would be silently wrong
    key = (
        bucket_tp, bucket_new, b, n_heads, greedy, top_k, nucleus,
        eos_id, moe_top_k, moe_dispatch, _params_fingerprint(params),
    )
    temperature = jnp.float32(temperature)
    top_p = jnp.float32(top_p)
    _serve_cache.record_request()
    # the rung sizes the compiled buffers; the REQUESTED budget rides in
    # as a traced operand, so the loop never decodes past the request
    budget = jnp.int32(max_new_tokens)
    compiled = _serve_cache.programs.get(key)
    if compiled is None:
        t0 = time.perf_counter()
        lowered = _generate_impl.lower(
            params, padded, start, budget, temperature, top_p, rng,
            n_heads=n_heads, max_new_tokens=bucket_new, greedy=greedy,
            top_k=top_k, nucleus=nucleus, eos_id=eos_id,
            moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
        )
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        _serve_cache.programs[key] = compiled
        _serve_cache.record_compile()
        # device/compile telemetry: the AOT path has the real compile
        # wall time AND the Compiled in hand, so the ledger entry gets
        # exact cost + memory analysis (graceful None where jax lacks
        # the API)
        device_telemetry.record_program(
            ("serve", bucket_tp, bucket_new, b, greedy, top_k, nucleus),
            compile_s,
            source="serve_cache",
            cost=(
                device_telemetry.stage_cost(compiled)
                or device_telemetry.stage_cost(lowered)
            ),
            memory=device_telemetry.compiled_memory(compiled),
            dedup=key,
        )
    else:
        _serve_cache.record_hit()
    out = compiled(params, padded, start, budget, temperature, top_p, rng)
    return out[:, pad: pad + tp + max_new_tokens]
