"""Checkpoint / resume.

Capability parity with ``veles/snapshotter.py`` + znicz ``NNSnapshotter``
[SURVEY.md 2.1 "Snapshotter", 3.5, 5.4]: periodic + on-best-validation
snapshots, optional compression, resume-and-continue.  Re-founded per
SURVEY.md §7: instead of pickling the live workflow object graph, a snapshot
is (a) the pure pytree train state (params/velocity/step/rng-key) converted
to numpy, and (b) an explicit host-state dict (decision, loader, prng
registry) — so checkpoints survive code refactors and process restarts.
"""

from __future__ import annotations

import gzip
import os
import pickle
import re
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu import observability

FORMAT_VERSION = 1


class _KeyLeaf(NamedTuple):
    """Pickle-safe stand-in for a typed jax PRNG key leaf."""

    data: np.ndarray
    impl: str


def _to_host(tree):
    def gather(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            if leaf.is_fully_replicated:  # local replica is the full value
                return np.asarray(leaf.addressable_data(0))
            # cross-host-sharded leaf (multi-host TP): every process joins
            # the allgather, each ends with the full array
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(leaf, tiled=True)
        return leaf

    def conv(leaf):
        if isinstance(leaf, jax.Array) and jnp.issubdtype(
            leaf.dtype, jax.dtypes.prng_key
        ):
            return _KeyLeaf(
                np.asarray(gather(jax.random.key_data(leaf))),
                str(jax.random.key_impl(leaf)),
            )
        return np.asarray(gather(leaf))

    return jax.tree_util.tree_map(conv, tree)


def _from_host(tree):
    def conv(leaf):
        if isinstance(leaf, _KeyLeaf):
            return jax.random.wrap_key_data(
                jnp.asarray(leaf.data), impl=leaf.impl
            )
        return leaf

    return jax.tree_util.tree_map(
        conv, tree, is_leaf=lambda x: isinstance(x, _KeyLeaf)
    )


def load_snapshot(path: str) -> Tuple[Any, Dict[str, Any]]:
    """Read a snapshot file -> (train_state, host_state).  Standalone so a
    resume never requires a snapshot-writing policy to be configured."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        payload = pickle.load(f)
    if payload.get("format_version") != FORMAT_VERSION:
        raise ValueError(
            f"snapshot {path} has format {payload.get('format_version')}, "
            f"expected {FORMAT_VERSION}"
        )
    return _from_host(payload["train_state"]), payload["host_state"]


class Snapshotter:
    """Write/read snapshots under ``directory`` with a filename ``prefix``.

    ``interval``: also snapshot every N epochs regardless of improvement
    (0 = only on improvement).  ``keep``: retain at most N non-best snapshots
    (best is always kept).
    """

    def __init__(
        self,
        directory: str,
        prefix: str = "workflow",
        *,
        compress: bool = True,
        interval: int = 0,
        keep: int = 3,
        save_best: bool = True,
    ):
        self.directory = directory
        self.prefix = prefix
        self.compress = compress
        self.interval = interval
        self.keep = keep
        # save_best=False: interval-only snapshots.  Under the workflow's
        # deferred epoch sync, best saves write from a retained one-epoch
        # state buffer (improvement is only known one epoch late); interval
        # epochs are known in advance and flush synchronously.
        self.save_best = save_best
        # multi-host: the Workflow sets writer=False on non-coordinator
        # processes — they still participate in save()'s (possibly
        # collective) device->host readback, but never touch the filesystem
        self.writer = True
        os.makedirs(directory, exist_ok=True)
        # Recover periodic snapshots from a previous process so "keep at
        # most N" holds across restarts, oldest (lowest epoch tag) first.
        existing = []
        for fname in os.listdir(directory):
            m = re.fullmatch(
                re.escape(prefix) + r"_epoch(\d+)\.pickle(\.gz)?", fname
            )
            if m:
                existing.append((int(m.group(1)), os.path.join(directory, fname)))
        self._kept: list = [p for _, p in sorted(existing)]

    # -- paths ---------------------------------------------------------------
    def _path(self, tag: str) -> str:
        ext = ".pickle.gz" if self.compress else ".pickle"
        return os.path.join(self.directory, f"{self.prefix}_{tag}{ext}")

    @property
    def best_path(self) -> str:
        return self._path("best")

    # -- save/load -----------------------------------------------------------
    def save(
        self,
        train_state,
        host_state: Optional[Dict[str, Any]] = None,
        *,
        tag: str,
    ) -> str:
        # spans land the snapshot cost on the Perfetto timeline next to
        # the train/serve phases it steals wall time from: gather is the
        # (possibly collective) device->host readback, write the
        # pickle+fsync-side file cost
        with observability.span("snapshot/save", tag=tag):
            with observability.span("snapshot/gather"):
                payload = {
                    "format_version": FORMAT_VERSION,
                    # collective on multi-host
                    "train_state": _to_host(train_state),
                    "host_state": host_state or {},
                }
            path = self._path(tag)
            if not self.writer:
                return path  # bookkeeping stays identical across processes
            opener = gzip.open if self.compress else open
            tmp = path + ".tmp"
            with observability.span("snapshot/write", path=path):
                with opener(tmp, "wb") as f:
                    pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
                os.replace(tmp, path)
        return path

    def load(self, path: str) -> Tuple[Any, Dict[str, Any]]:
        return load_snapshot(path)

    def maybe_save(
        self,
        train_state,
        host_state: Optional[Dict[str, Any]] = None,
        *,
        epoch: int,
        improved: bool,
    ) -> Optional[str]:
        """Snapshot policy: on validation improvement -> overwrite 'best'
        (unless ``save_best=False``); every ``interval`` epochs -> tagged
        periodic snapshot."""
        path = None
        if improved and self.save_best:
            path = self.save(train_state, host_state, tag="best")
        if self.interval and (epoch + 1) % self.interval == 0:
            path = self.save(train_state, host_state, tag=f"epoch{epoch}")
            self._kept.append(path)
            while len(self._kept) > self.keep:
                old = self._kept.pop(0)
                # only the writer touches the filesystem (multi-host
                # processes share bookkeeping but must not race on removes)
                if self.writer and os.path.exists(old):
                    os.remove(old)
        return path
