"""Checkpoint / resume.

Capability parity with ``veles/snapshotter.py`` + znicz ``NNSnapshotter``
[SURVEY.md 2.1 "Snapshotter", 3.5, 5.4]: periodic + on-best-validation
snapshots, optional compression, resume-and-continue.  Re-founded per
SURVEY.md §7: instead of pickling the live workflow object graph, a snapshot
is (a) the pure pytree train state (params/velocity/step/rng-key) converted
to numpy, and (b) an explicit host-state dict (decision, loader, prng
registry) — so checkpoints survive code refactors and process restarts.

Crash safety (docs/TRAINING.md "Self-healing training"): every snapshot
is written atomically (tmp + ``os.replace``) with a sha256 **integrity
sidecar** (``<file>.sha256``) committed only after the data file, so a
crash at any byte leaves either the previous snapshot intact or a
digest-mismatched file the loaders treat as corrupt.  ``load_snapshot``
raises a typed :class:`SnapshotCorruptError` on truncation / digest
mismatch / undecodable payload instead of a bare ``pickle``/``EOFError``,
and :func:`find_latest_valid` walks a directory newest→oldest past
corrupt files so a resume always lands on a verifiable checkpoint.
"""

from __future__ import annotations

import gzip
import hashlib
import logging
import os
import pickle
import re
import zlib
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu import observability
from znicz_tpu.observability import pipeline as _pipeline
from znicz_tpu.utils import faults

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1

SIDECAR_SUFFIX = ".sha256"

# snapshot files a Snapshotter (any prefix) may have written
_SNAPSHOT_RE = re.compile(r".+\.pickle(\.gz)?$")


class SnapshotCorruptError(RuntimeError):
    """The snapshot file exists but cannot be trusted: truncated,
    digest-mismatched against its sidecar, or undecodable."""


class SnapshotWriteError(RuntimeError):
    """Writing a snapshot failed (disk full, permissions, injected
    fault).  The previous snapshot is untouched — ``maybe_save``
    swallows this (counted + logged) so a flaky disk costs a
    checkpoint, never the run."""


class _KeyLeaf(NamedTuple):
    """Pickle-safe stand-in for a typed jax PRNG key leaf."""

    data: np.ndarray
    impl: str


def _to_host(tree):
    def gather(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            if leaf.is_fully_replicated:  # local replica is the full value
                return np.asarray(leaf.addressable_data(0))
            # cross-host-sharded leaf (multi-host TP): every process joins
            # the allgather, each ends with the full array
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(leaf, tiled=True)
        return leaf

    def conv(leaf):
        if isinstance(leaf, jax.Array) and jnp.issubdtype(
            leaf.dtype, jax.dtypes.prng_key
        ):
            return _KeyLeaf(
                np.asarray(gather(jax.random.key_data(leaf))),
                str(jax.random.key_impl(leaf)),
            )
        return np.asarray(gather(leaf))

    return jax.tree_util.tree_map(conv, tree)


def _from_host(tree):
    def conv(leaf):
        if isinstance(leaf, _KeyLeaf):
            return jax.random.wrap_key_data(
                jnp.asarray(leaf.data), impl=leaf.impl
            )
        return leaf

    return jax.tree_util.tree_map(
        conv, tree, is_leaf=lambda x: isinstance(x, _KeyLeaf)
    )


def _sidecar_path(path: str) -> str:
    return path + SIDECAR_SUFFIX


def _digest_file(path: str) -> str:
    """Chunked sha256 of a file — snapshots can be multi-GB; neither
    the save nor the load/verify path may hold one in RAM to hash it."""
    hasher = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            hasher.update(chunk)
    return hasher.hexdigest()


def _sidecar_fields(path: str) -> Optional[List[str]]:
    """The sidecar's whitespace fields (digest, basename, vN...), or
    None when no sidecar exists (a pre-sidecar snapshot)."""
    sidecar = _sidecar_path(path)
    if not os.path.exists(sidecar):
        return None
    with open(sidecar) as f:
        return f.read().strip().split()


def _check_sidecar(path: str) -> bool:
    """Digest-check ``path`` against its sidecar (chunked read).
    Returns True when a sidecar existed and matched, False when there
    is none; raises :class:`SnapshotCorruptError` on a mismatch."""
    fields = _sidecar_fields(path)
    if fields is None:
        return False
    want = fields[0] if fields else ""
    got = _digest_file(path)
    if want != got:
        raise SnapshotCorruptError(
            f"snapshot {path} fails its sha256 sidecar check "
            f"(want {want[:12]}..., got {got[:12]}...) — truncated "
            "or partially overwritten; resume from an older snapshot"
        )
    return True


def _decode_file(path: str) -> dict:
    """Streamed file -> payload dict (no full-file resident copy);
    every decode failure mode becomes the one typed
    :class:`SnapshotCorruptError`."""
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rb") as f:
            payload = pickle.load(f)
    except (
        pickle.UnpicklingError,
        EOFError,
        gzip.BadGzipFile,
        zlib.error,
        AttributeError,  # missing class on unpickle
        MemoryError,
        IndexError,
        KeyError,
        UnicodeDecodeError,
    ) as exc:
        raise SnapshotCorruptError(
            f"snapshot {path} is unreadable ({type(exc).__name__}: {exc})"
        ) from exc
    if not isinstance(payload, dict):
        raise SnapshotCorruptError(
            f"snapshot {path} decodes to {type(payload).__name__}, "
            "not a snapshot payload"
        )
    return payload


def load_snapshot(path: str) -> Tuple[Any, Dict[str, Any]]:
    """Read a snapshot file -> (train_state, host_state).  Standalone so a
    resume never requires a snapshot-writing policy to be configured.

    Raises :class:`SnapshotCorruptError` on truncation, sidecar digest
    mismatch or an undecodable payload (never a bare pickle error), and
    ``ValueError`` on a format-version mismatch (a valid file this code
    doesn't speak — not corruption)."""
    try:
        faults.fire("snapshot.load")
    except faults.FaultInjected as exc:
        # the chaos point simulates an unreadable checkpoint: typed,
        # so find_latest_valid / rollback fall through to older ones
        raise SnapshotCorruptError(
            f"snapshot {path} unreadable (injected)"
        ) from exc
    _check_sidecar(path)
    payload = _decode_file(path)
    if payload.get("format_version") != FORMAT_VERSION:
        raise ValueError(
            f"snapshot {path} has format {payload.get('format_version')}, "
            f"expected {FORMAT_VERSION}"
        )
    try:
        train_state, host_state = payload["train_state"], payload["host_state"]
    except KeyError as exc:
        raise SnapshotCorruptError(
            f"snapshot {path} payload is missing {exc}"
        ) from exc
    return _from_host(train_state), host_state


def verify_snapshot(path: str) -> None:
    """Cheap usability check: sidecar digest (and the format version it
    records) when present, else a full decode attempt.  Raises
    :class:`SnapshotCorruptError` on untrustworthy bytes, ``ValueError``
    on a version-skewed (valid but unloadable) snapshot, OSError on an
    unreadable file; returns None when the snapshot is resumable."""
    if _check_sidecar(path):
        # version skew recorded in the sidecar: the file is intact but
        # load_snapshot would reject it — find_latest_valid must fall
        # through to an older COMPATIBLE snapshot instead of handing
        # the launcher a checkpoint that crash-loops the supervisor
        for field in (_sidecar_fields(path) or [])[2:]:
            if field.startswith("v") and field[1:].isdigit():
                if int(field[1:]) != FORMAT_VERSION:
                    raise ValueError(
                        f"snapshot {path} has format {field[1:]}, "
                        f"expected {FORMAT_VERSION}"
                    )
        return
    # pre-sidecar snapshot: the only way to verify is to decode it
    payload = _decode_file(path)
    if payload.get("format_version") != FORMAT_VERSION:
        raise ValueError(
            f"snapshot {path} has format "
            f"{payload.get('format_version')}, expected {FORMAT_VERSION}"
        )


def is_valid_snapshot(path: str) -> bool:
    try:
        verify_snapshot(path)
        return True
    except Exception:
        logger.warning("snapshot %s failed verification", path, exc_info=True)
        return False


def find_latest_valid(
    directory: str,
    prefix: Optional[str] = None,
    *,
    exclude=(),
) -> Optional[str]:
    """Newest verifiable snapshot in ``directory`` (by mtime, newest
    first), or None.  Corrupt / truncated / unreadable files are logged
    and skipped — resume always lands on a checkpoint that passes
    :func:`verify_snapshot`, or starts fresh.  ``exclude``: paths the
    caller already tried and found unloadable (verification is a digest
    check, so a digest-valid file can still fail to unpickle — e.g. a
    since-renamed class; the launcher quarantines it and asks again)."""
    try:
        names = os.listdir(directory)
    # an absent/unreadable directory has no snapshots to offer
    except OSError:  # znicz-check: disable=ZNC008
        return None
    excluded = set(exclude)
    candidates: List[Tuple[float, str]] = []
    for name in names:
        if not _SNAPSHOT_RE.fullmatch(name):
            continue
        if prefix is not None and not name.startswith(prefix + "_"):
            continue
        path = os.path.join(directory, name)
        if path in excluded:
            continue
        try:
            candidates.append((os.path.getmtime(path), path))
        # deleted between listdir and stat: not a candidate
        except OSError:  # znicz-check: disable=ZNC008
            continue
    for _, path in sorted(candidates, reverse=True):
        if is_valid_snapshot(path):
            return path
    return None


class Snapshotter:
    """Write/read snapshots under ``directory`` with a filename ``prefix``.

    ``interval``: also snapshot every N epochs regardless of improvement
    (0 = only on improvement).  ``keep``: retain at most N non-best snapshots
    (best is always kept) — pruning counts VERIFIED snapshots, so the only
    remaining valid checkpoint is never deleted even when newer files are
    corrupt.
    """

    def __init__(
        self,
        directory: str,
        prefix: str = "workflow",
        *,
        compress: bool = True,
        interval: int = 0,
        keep: int = 3,
        save_best: bool = True,
    ):
        self.directory = directory
        self.prefix = prefix
        self.compress = compress
        self.interval = interval
        self.keep = keep
        # save_best=False: interval-only snapshots.  Under the workflow's
        # deferred epoch sync, best saves write from a retained one-epoch
        # state buffer (improvement is only known one epoch late); interval
        # epochs are known in advance and flush synchronously.
        self.save_best = save_best
        # multi-host: the Workflow sets writer=False on non-coordinator
        # processes — they still participate in save()'s (possibly
        # collective) device->host readback, but never touch the filesystem
        self.writer = True
        self._m_failures = observability.counter(
            _pipeline.SNAPSHOT_FAILURES_METRIC,
            "snapshot writes that failed (previous snapshot left intact)",
        )
        # paths THIS process wrote successfully: prune() trusts them
        # without re-reading multi-GB files to re-hash a digest this
        # process computed moments earlier
        self._verified: set = set()
        os.makedirs(directory, exist_ok=True)
        # Recover periodic snapshots from a previous process so "keep at
        # most N" holds across restarts, oldest (lowest epoch tag) first.
        existing = []
        for fname in os.listdir(directory):
            m = re.fullmatch(
                re.escape(prefix) + r"_epoch(\d+)\.pickle(\.gz)?", fname
            )
            if m:
                existing.append((int(m.group(1)), os.path.join(directory, fname)))
        self._kept: list = [p for _, p in sorted(existing)]

    # -- paths ---------------------------------------------------------------
    def _path(self, tag: str) -> str:
        ext = ".pickle.gz" if self.compress else ".pickle"
        return os.path.join(self.directory, f"{self.prefix}_{tag}{ext}")

    @property
    def best_path(self) -> str:
        return self._path("best")

    # -- save/load -----------------------------------------------------------
    def save(
        self,
        train_state,
        host_state: Optional[Dict[str, Any]] = None,
        *,
        tag: str,
    ) -> str:
        # spans land the snapshot cost on the Perfetto timeline next to
        # the train/serve phases it steals wall time from: gather is the
        # (possibly collective) device->host readback, write the
        # pickle+fsync-side file cost
        with observability.span("snapshot/save", tag=tag):
            with observability.span("snapshot/gather"):
                payload = {
                    "format_version": FORMAT_VERSION,
                    # collective on multi-host
                    "train_state": _to_host(train_state),
                    "host_state": host_state or {},
                }
            path = self._path(tag)
            if not self.writer:
                return path  # bookkeeping stays identical across processes
            opener = gzip.open if self.compress else open
            tmp = path + ".tmp"
            with observability.span("snapshot/write", path=path):
                replaced = False
                try:
                    faults.fire("snapshot.write")
                    with opener(tmp, "wb") as f:
                        pickle.dump(
                            payload, f, protocol=pickle.HIGHEST_PROTOCOL
                        )
                    # chunked hash: never hold a multi-GB serialized
                    # snapshot in host RAM beside the payload
                    digest = _digest_file(tmp)
                    # data file first, sidecar second: a crash in the
                    # window leaves a new file with the OLD sidecar —
                    # a digest MISMATCH the loaders skip, never a
                    # silently-trusted torn snapshot
                    os.replace(tmp, path)
                    replaced = True
                    side_tmp = _sidecar_path(path) + ".tmp"
                    with open(side_tmp, "w") as f:
                        f.write(
                            f"{digest}  {os.path.basename(path)}  "
                            f"v{FORMAT_VERSION}\n"
                        )
                    os.replace(side_tmp, _sidecar_path(path))
                except (OSError, faults.FaultInjected) as exc:
                    for leftover in (tmp, _sidecar_path(path) + ".tmp"):
                        try:
                            os.remove(leftover)
                        # best-effort cleanup on the failure path
                        except OSError:  # znicz-check: disable=ZNC008
                            pass
                    if replaced:
                        # the NEW data file already landed (the replace
                        # succeeded; only the sidecar write failed): a
                        # stale sidecar from the previous write would
                        # condemn the good new file forever — drop it
                        # so the file verifies by decode, and report
                        # SUCCESS (the checkpoint exists; callers must
                        # track it for retention/resume)
                        try:
                            os.remove(_sidecar_path(path))
                        except OSError:  # znicz-check: disable=ZNC008
                            pass
                        logger.warning(
                            "snapshot %s written but its sidecar "
                            "failed (%s); it will verify by decode",
                            path, exc,
                        )
                        return path
                    raise SnapshotWriteError(
                        f"snapshot write to {path} failed: {exc}"
                    ) from exc
            self._verified.add(path)
        return path

    def load(self, path: str) -> Tuple[Any, Dict[str, Any]]:
        return load_snapshot(path)

    def maybe_save(
        self,
        train_state,
        host_state: Optional[Dict[str, Any]] = None,
        *,
        epoch: int,
        improved: bool,
    ) -> Optional[str]:
        """Snapshot policy: on validation improvement -> overwrite 'best'
        (unless ``save_best=False``); every ``interval`` epochs -> tagged
        periodic snapshot.  A :class:`SnapshotWriteError` is swallowed
        (counted + logged): a failed checkpoint must not kill the run —
        the previous snapshot is intact and the next interval retries."""
        path = None
        if improved and self.save_best:
            try:
                path = self.save(train_state, host_state, tag="best")
            except SnapshotWriteError:
                self._m_failures.inc()
                logger.exception("best-snapshot write failed; continuing")
                path = None
        if self.interval and (epoch + 1) % self.interval == 0:
            try:
                path = self.save(train_state, host_state, tag=f"epoch{epoch}")
            except SnapshotWriteError:
                self._m_failures.inc()
                logger.exception(
                    "epoch%d snapshot write failed; continuing", epoch
                )
                return None
            self._kept.append(path)
            self.prune()
        return path

    def prune(self) -> None:
        """Apply the ``keep`` retention bound to periodic snapshots.

        Prunes by the VERIFIED set, not filename count: an old snapshot
        is only deleted while at least one newer kept snapshot passes
        :func:`verify_snapshot` — when every newer file is corrupt, the
        old valid one is retained past the bound (the bound is a disk
        budget; an unresumable run is a lost run)."""
        while len(self._kept) > self.keep:
            old = self._kept[0]
            if self.writer and os.path.exists(old):
                # newest first: the just-written (in-_verified) path
                # short-circuits the sweep, so a restart-recovered
                # ledger never re-hashes old multi-GB files per save
                newer_valid = any(
                    os.path.exists(p)
                    and (p in self._verified or is_valid_snapshot(p))
                    for p in reversed(self._kept[1:])
                )
                if not newer_valid:
                    logger.warning(
                        "retaining %s past keep=%d: no newer snapshot "
                        "verifies", old, self.keep,
                    )
                    return
            self._kept.pop(0)
            self._verified.discard(old)
            # only the writer touches the filesystem (multi-host
            # processes share bookkeeping but must not race on removes)
            if self.writer:
                for victim in (old, _sidecar_path(old)):
                    if os.path.exists(victim):
                        os.remove(victim)
