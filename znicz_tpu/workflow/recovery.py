"""Training self-healing policy: anomaly-triggered rollback + typed exits.

PR 13's flight recorder made training failures *visible* — a NaN loss
rings the :class:`~znicz_tpu.observability.anomaly.StepAnomalyDetector`
and ``znicz-doctor`` exits 1 — but nothing *acted* on a verdict: the
run kept burning steps on poisoned state.  This module is the acting
half (docs/TRAINING.md "Self-healing training"):

* :class:`RecoveryPolicy` — consumes the detector's typed verdicts and
  decides when the workflow rolls back to its last good snapshot, how
  the replay is perturbed (advance the shuffle stream and/or scale the
  learning rate down) and when to give up (bounded rollback budget ->
  typed :class:`RollbackExhaustedError`).
* :class:`TrainingPreempted` — the control-flow exception a
  SIGTERM/SIGINT-initiated graceful stop raises after the in-flight
  step drained and the emergency snapshot was written; the launcher
  maps it to :data:`EXIT_PREEMPTED`.

The policy object is host-side bookkeeping only (no jax): the rollback
mechanics — state restore, PRNG/loader/decision rewind — live in
:class:`~znicz_tpu.workflow.workflow.Workflow`, which re-feeds the
ALREADY-COMPILED train step, so recovery adds zero new XLA programs.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from znicz_tpu import observability
from znicz_tpu.observability import anomaly as _anomaly
from znicz_tpu.observability import pipeline as _pipeline

# documented process exit code for a graceful preemption (SIGTERM/
# SIGINT): "the run was interrupted, an emergency snapshot exists,
# resume me" — distinct from 0 (done) and 1 (crash).  75 is EX_TEMPFAIL
# ("temporary failure, retry"), exactly the supervisor's restart hint.
EXIT_PREEMPTED = 75

# verdict types that mean "the train state itself is poisoned" — a
# rollback is the only fix (continuing trains garbage)
NON_FINITE_TYPES = (
    _anomaly.NON_FINITE_LOSS,
    _anomaly.NON_FINITE_GRAD,
)


class RollbackExhaustedError(RuntimeError):
    """The recovery policy gave up: the rollback budget is spent (or no
    valid snapshot exists to roll back to).  The run is not healing
    itself — surface to the operator instead of looping."""


class TrainingPreempted(Exception):
    """Graceful-stop control flow: raised by the workflow after a
    requested stop drained the in-flight step and wrote the emergency
    snapshot.  ``snapshot_path`` is None when no snapshotter was
    configured (nothing durable could be written)."""

    def __init__(self, message: str, snapshot_path: Optional[str] = None):
        super().__init__(message)
        self.snapshot_path = snapshot_path


class RecoveryPolicy:
    """When/how training rolls back to the last good snapshot.

    ``max_rollbacks``: total rollback budget for the run; exceeding it
    raises :class:`RollbackExhaustedError` (typed give-up, surfaced as
    the ``znicz_train_rollback_give_up`` gauge).
    ``lr_backoff``: multiply the effective learning-rate scale by this
    on every rollback (1.0 = keep the schedule; the scale composes with
    the workflow's ``lr_policy``).
    ``perturb``: advance the loader's shuffle stream after the restore
    so the replayed data window differs — a data-order-dependent blowup
    doesn't deterministically recur.  Leave False (with
    ``lr_backoff=1.0``) for byte-exact replay, e.g. golden tests.
    ``rollback_on_spike``: 0 disables; N > 0 also rolls back after N
    ``loss_spike`` verdicts since the last rollback (non-finite
    verdicts always trigger).
    """

    def __init__(
        self,
        *,
        max_rollbacks: int = 2,
        lr_backoff: float = 0.5,
        perturb: bool = True,
        rollback_on_spike: int = 0,
    ):
        if max_rollbacks < 1:
            raise ValueError("max_rollbacks must be >= 1")
        if not 0.0 < lr_backoff <= 1.0:
            raise ValueError("lr_backoff must be in (0, 1]")
        if rollback_on_spike < 0:
            raise ValueError("rollback_on_spike must be >= 0")
        self.max_rollbacks = int(max_rollbacks)
        self.lr_backoff = float(lr_backoff)
        self.perturb = bool(perturb)
        self.rollback_on_spike = int(rollback_on_spike)
        # run state
        self.rollbacks_used = 0
        self.lr_scale = 1.0
        self.gave_up = False
        self.events: List[dict] = []
        self._spikes_since_rollback = 0
        self._m_rollbacks = observability.counter(
            _pipeline.ROLLBACKS_METRIC,
            "anomaly-triggered training rollbacks by verdict reason",
            ("reason",),
        )
        self._m_give_up = observability.gauge(
            _pipeline.ROLLBACK_GIVE_UP_METRIC,
            "1 once the recovery policy gave up (rollback budget spent "
            "or no valid snapshot) — znicz-doctor's exit-1 gate",
        )

    # -- decision ----------------------------------------------------------
    def should_rollback(self, anomalies: List[dict]) -> Optional[str]:
        """Map a batch of detector verdicts to a rollback reason (the
        verdict type that triggered), or None to keep training."""
        for a in anomalies:
            if a.get("type") in NON_FINITE_TYPES:
                return a["type"]
        if self.rollback_on_spike:
            spikes = sum(
                1 for a in anomalies
                if a.get("type") == _anomaly.LOSS_SPIKE
            )
            if spikes:
                self._spikes_since_rollback += spikes
                if self._spikes_since_rollback >= self.rollback_on_spike:
                    return _anomaly.LOSS_SPIKE
        return None

    # -- bookkeeping (the workflow calls these around the restore) ---------
    def budget_left(self) -> bool:
        return self.rollbacks_used < self.max_rollbacks

    def note_rollback(
        self, reason: str, *, step: int, source: str
    ) -> dict:
        """Record one executed rollback: budget, counter, lr backoff."""
        self.rollbacks_used += 1
        self.lr_scale *= self.lr_backoff
        self._spikes_since_rollback = 0
        self._m_rollbacks.labels(reason=reason).inc()
        event = {
            "kind": "rollback",
            "reason": reason,
            "step": int(step),
            "source": source,
            "rollbacks_used": self.rollbacks_used,
            "lr_scale": self.lr_scale,
            "unix": time.time(),  # timestamp, not a duration
        }
        self.events.append(event)
        return event

    def note_give_up(self, reason: str, *, step: int, why: str) -> None:
        self.gave_up = True
        self._m_give_up.set(1.0)
        self.events.append(
            {
                "kind": "give_up",
                "reason": reason,
                "step": int(step),
                "why": why,
                "unix": time.time(),  # timestamp, not a duration
            }
        )

    def report(self) -> Dict[str, object]:
        """JSON-able readout for ``status.json["recovery"]``."""
        return {
            "rollbacks_used": self.rollbacks_used,
            "max_rollbacks": self.max_rollbacks,
            "lr_scale": self.lr_scale,
            "gave_up": self.gave_up,
            "events": [dict(e) for e in self.events],
        }
